"""Mesh-sharded batch engine acceptance (ISSUE 7).

Pins:
- sharded pooled execution bit-exact, query by query, against the
  single-device per-set ``BatchEngine`` loop across (op x mesh shape x
  placement) — including through the mesh -> single -> sequential guard
  ladder under injected faults;
- the per-shard HBM-budget property: proactive splits fire BEFORE
  dispatch while the PER-SHARD predicted transient exceeds the budget,
  every dispatched launch's per-shard prediction fits it, and at the
  same budget the single-device pooled engine proactively splits >= 2x
  more (the capacity scaling the mesh buys);
- resident capacity: sharded placement puts exactly 1/mesh_rows of the
  pooled row image on each row-shard (verified from the placed array's
  addressable shards) and the HBM ledger carries the pool;
- the S=1 ledger pin: dispatches register no new resident buffers;
- the ``batch.shard`` mesh-keyed event / ``sharded.*`` span vocabulary
  and the ``rb_shard_balance`` / ``rb_sharded_*`` metrics;
- warmup + persistent compile cache (ROADMAP item 3): ``warmup()``
  pre-compiles the programs a matching execute then cache-hits, and
  ``ROARING_TPU_COMPILE_CACHE`` points JAX's persistent cache at the
  requested directory.
"""

import dataclasses
import gc
import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.parallel import (BatchEngine, BatchGroup, BatchQuery,
                                        MultiSetBatchEngine,
                                        ShardedBatchEngine, SpecLayout,
                                        default_mesh)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.runtime import warmup as rt_warmup

S_SIZES = (8, 6, 8)


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()


def _mesh(rows: int, data: int = 1) -> Mesh:
    devs = np.array(jax.devices()[:rows * data]).reshape(rows, data)
    return Mesh(devs, ("rows", "data"))


@pytest.fixture(scope="module")
def tenant_bitmaps():
    """Three tenants with different shapes (sparse uniform / dense chunk
    / run-heavy) — the multiset acceptance fixture's recipe."""
    rng = np.random.default_rng(0x5AAD)
    out = []
    for s, n in enumerate(S_SIZES):
        bms = []
        for i in range(n):
            vals = [rng.integers(0, 1 << 17, 2000).astype(np.uint32)]
            if s == 1 and i % 2 == 0:
                vals.append(np.arange(1 << 16, (1 << 16) + 9000,
                                      dtype=np.uint32))
            if s == 2:
                start = int(rng.integers(0, 1 << 16))
                vals.append(np.arange(start, start + 1500,
                                      dtype=np.uint32))
            bms.append(RoaringBitmap.from_values(
                np.unique(np.concatenate(vals))))
        out.append(bms)
    return out


@pytest.fixture(scope="module")
def engines(tenant_bitmaps):
    return [BatchEngine.from_bitmaps(t, layout="dense")
            for t in tenant_bitmaps]


@pytest.fixture(scope="module")
def pool():
    """Every op on every tenant, materialized bitmaps — the (op x set)
    coverage matrix as one pool."""
    groups = []
    for sid, n in enumerate(S_SIZES):
        groups.append(BatchGroup(sid, [
            BatchQuery("or", (0, 1, 2), form="bitmap"),
            BatchQuery("and", (1, 2, 3), form="bitmap"),
            BatchQuery("xor", (0, 2, 4), form="bitmap"),
            BatchQuery("andnot", (0, 1, 3), form="bitmap"),
            BatchQuery("or", tuple(range(n)), form="bitmap"),
        ]))
    return groups


@pytest.fixture(scope="module")
def oracle(engines, pool):
    """The single-device per-set BatchEngine loop every mesh shape must
    match bit-exactly."""
    return [engines[g.set_id].execute(list(g.queries), engine="xla")
            for g in pool]


def _assert_bit_exact(got, want, tag):
    for gi, (grows, wrows) in enumerate(zip(got, want)):
        assert len(grows) == len(wrows)
        for qi, (a, b) in enumerate(zip(grows, wrows)):
            assert a.cardinality == b.cardinality, (tag, gi, qi)
            if b.bitmap is not None:
                assert a.bitmap == b.bitmap, (tag, gi, qi)


@pytest.mark.parametrize("shape,placement", [
    ((1, 1), "sharded"),
    ((2, 1), "sharded"),
    ((4, 1), "sharded"),
    ((8, 1), "sharded"),
    ((2, 2), "sharded"),
    ((4, 1), "replicated"),
])
def test_sharded_matches_single_device(engines, pool, oracle, shape,
                                       placement):
    """The (op x mesh shape x placement) parity matrix: pooled launches
    over the mesh bit-exact against the single-device per-set loop."""
    eng = ShardedBatchEngine(engines, mesh=_mesh(*shape),
                             placement=placement)
    got = eng.execute(pool)
    _assert_bit_exact(got, oracle, (shape, placement))
    # raw mesh rung too (no guard, no injection)
    got = eng.execute(pool, fallback=False)
    _assert_bit_exact(got, oracle, (shape, placement, "raw"))


def test_single_set_query_sugar(engines):
    """A bare BatchQuery list runs as a one-tenant pool and returns a
    flat list, bit-exact vs that set's BatchEngine."""
    eng = ShardedBatchEngine(engines[0], mesh=_mesh(4))
    qs = [BatchQuery("or", (0, 1, 2), form="bitmap"),
          BatchQuery("andnot", (0, 3, 4)),
          BatchQuery("and", (1, 2)),
          BatchQuery("xor", (0, 5), form="bitmap")]
    got = eng.execute(qs)
    want = engines[0].execute(qs, engine="xla")
    assert [r.cardinality for r in got] == [r.cardinality for r in want]
    assert got[0].bitmap == want[0].bitmap
    assert got[3].bitmap == want[3].bitmap


def test_mesh_demotes_to_single_device_then_sequential(engines, pool,
                                                       oracle):
    """The mesh -> single -> sequential ladder under ROARING_TPU_FAULTS:
    a dead mesh rung lands on the un-sharded pooled engine, a dead
    everything lands on the host sequential reference — bit-exact each
    way, demotions counted."""
    eng = ShardedBatchEngine(engines, mesh=_mesh(4))
    with faults.inject("lowering@mesh=1.0:0xD1"):
        got = eng.execute(pool)
    _assert_bit_exact(got, oracle, "mesh->single")
    stats = guard.dispatch_stats("sharded_engine")
    assert stats["demotions"] >= 1 and stats["sequential"] == 0
    guard.reset_dispatch_stats()
    with faults.inject("lowering=1.0:0xD2"):   # every device rung dead
        got = eng.execute(pool)
    _assert_bit_exact(got, oracle, "sequential-floor")
    assert guard.dispatch_stats("sharded_engine")["sequential"] >= 1
    # oom injection: reactive pool halving stays bit-exact
    with faults.inject("oom@mesh=0.5:0xD3"):
        got = eng.execute(pool)
    _assert_bit_exact(got, oracle, "oom")


def test_per_shard_budget_split_property(engines, pool, oracle, tmp_path):
    """The per-shard proactive split: splits fire BEFORE dispatch, every
    dispatched launch's PER-SHARD prediction fits the budget (from the
    sharded.memory trace events), results stay bit-exact, counted under
    rb_sharded_*."""
    eng = ShardedBatchEngine(engines, mesh=_mesh(4))
    full = eng.predict_dispatch_bytes(pool)
    assert full["per_shard_bytes"] > 0
    budget = max(1, full["per_shard_bytes"] // 2)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    policy = guard.GuardPolicy(hbm_budget=budget)
    got = eng.execute(pool, policy=policy)
    obs.disable()
    _assert_bit_exact(got, oracle, "budget")
    assert eng.proactive_split_count > 0

    spans = [json.loads(line) for line in open(path)]
    mems = [ev for s in spans if s["name"] == "sharded.dispatch"
            for ev in s["events"] if ev["name"] == "sharded.memory"]
    assert mems and all(ev["per_shard_predicted_bytes"] <= budget
                        for ev in mems)
    splits = [ev for s in spans for ev in s["events"]
              if ev["name"] == "proactive_split"
              and ev.get("site") == "sharded_engine"]
    assert len(splits) == eng.proactive_split_count
    assert all(ev["predicted_bytes"] > ev["budget_bytes"]
               for ev in splits)
    snap = obs.snapshot()
    pro = snap["counters"]["rb_sharded_proactive_splits_total"]
    assert pro[0]["value"] == eng.proactive_split_count


def test_sharded_splits_at_least_2x_less_than_single_device(engines,
                                                            pool):
    """The capacity acceptance: at the SAME per-device HBM budget, the
    4-row mesh executes a pool the single-device engine must proactively
    split >= 2x more — per-shard transients are ~1/4 of the pooled
    total, so the mesh admits what one chip cannot."""
    sh = ShardedBatchEngine(engines, mesh=_mesh(4))
    single = MultiSetBatchEngine(engines)
    budget = max(1, sh.predict_dispatch_bytes(pool)["per_shard_bytes"]
                 // 2)
    policy = guard.GuardPolicy(hbm_budget=budget)
    got_sh = sh.execute(pool, policy=policy)
    got_single = single.execute(pool, engine="xla", policy=policy)
    _assert_bit_exact(got_sh, got_single, "split-parity")
    assert sh.proactive_split_count >= 1
    assert single.proactive_split_count >= 2 * sh.proactive_split_count, (
        single.proactive_split_count, sh.proactive_split_count)


def test_resident_capacity_per_shard(engines):
    """Sharded placement puts exactly 1/mesh_rows of the (padded) pooled
    row image on each row-shard; replicated placement a full copy per
    device.  The HBM ledger carries the pool either way."""
    before = obs_memory.LEDGER.resident_bytes("sharded_pool")
    eng = ShardedBatchEngine(engines, mesh=_mesh(4),
                             placement="sharded")
    per_shard_rows = eng.pool_rows // 4
    for shard in eng.pool_words.addressable_shards:
        assert shard.data.shape == (per_shard_rows, 2048)
    assert eng.hbm_bytes() == eng.pool_rows * insights.ROW_BYTES
    assert (obs_memory.LEDGER.resident_bytes("sharded_pool") - before
            == eng.hbm_bytes())
    repl = ShardedBatchEngine(engines, mesh=_mesh(2),
                              placement="replicated")
    for shard in repl.pool_words.addressable_shards:
        assert shard.data.shape == (repl.pool_rows, 2048)
    assert repl.hbm_bytes() == repl.pool_rows * insights.ROW_BYTES * 2
    assert repl.shard_balance == 1.0
    # sharded placement on a data>1 mesh: each row-shard replicates
    # along the data axis, so the mesh holds data_size copies and the
    # ledger/hbm_bytes must count them
    sq = ShardedBatchEngine(engines, mesh=_mesh(2, 2),
                            placement="sharded")
    for shard in sq.pool_words.addressable_shards:
        assert shard.data.shape == (sq.pool_rows // 2, 2048)
    assert sq.hbm_bytes() == sq.pool_rows * insights.ROW_BYTES * 2
    assert (obs_memory.LEDGER.resident_bytes("sharded_pool") - before
            == eng.hbm_bytes() + repl.hbm_bytes() + sq.hbm_bytes())


def test_dispatch_registers_no_new_resident_buffers(engines):
    """The S=1 ledger pin: the pooled image registers once at build;
    executing (twice — plan/program cache warm and cold) moves nothing
    on the HBM ledger."""
    eng = ShardedBatchEngine(engines[0], mesh=_mesh(2))
    qs = [BatchQuery("or", (0, 1, 2)), BatchQuery("xor", (1, 3))]
    # the ledger releases entries via weakref.finalize, so dead owners
    # left behind by earlier test modules must be flushed before the
    # baseline snapshot or a GC pass inside the execute window shrinks
    # the ledger out from under the equality pin
    gc.collect()
    ledger_before = obs_memory.LEDGER.snapshot()
    eng.execute(qs)
    n_programs = len(eng._programs)
    eng.execute(qs)
    assert obs_memory.LEDGER.snapshot() == ledger_before
    assert len(eng._programs) == n_programs    # cache hit, no recompile


def test_batch_shard_event_and_mesh_metrics(engines, pool, tmp_path):
    """The mesh-keyed observability contract: sharded.* span vocabulary,
    a batch.shard event on every dispatch naming the mesh shape and the
    shard balance, per-shard memory accounting, mesh-labelled gauges."""
    eng = ShardedBatchEngine(engines, mesh=_mesh(2, 2))
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    eng.execute(pool)
    obs.disable()
    spans = [json.loads(line) for line in open(path)]
    names = {s["name"] for s in spans}
    assert {"sharded.execute", "sharded.plan", "sharded.pool",
            "sharded.dispatch", "sharded.readback"} <= names
    dispatches = [s for s in spans if s["name"] == "sharded.dispatch"]
    assert dispatches
    for s in dispatches:
        shard_evs = [ev for ev in s["events"]
                     if ev["name"] == "batch.shard"]
        assert shard_evs, "sharded.dispatch without a batch.shard event"
        ev = shard_evs[0]
        assert ev["mesh"] == [2, 2]
        assert ev["rows_per_shard"] > 0
        assert ev["shard_balance"] >= 1.0
        assert ev["per_shard_predicted_bytes"] > 0
        mems = [e for e in s["events"] if e["name"] == "sharded.memory"]
        assert mems and mems[0]["predicted_bytes"] > 0
        assert mems[0]["mesh"] == [2, 2]
        costs = [e for e in s["events"] if e["name"] == "sharded.cost"]
        assert costs and costs[0]["device_ms"] >= 0
        assert costs[0].get("devices") == 4
    mem_cell = obs_memory.dispatch_memory_cell(eng.last_dispatch_memory)
    assert mem_cell["mesh"] == [2, 2]
    assert mem_cell["per_shard_predicted_mb"] > 0
    snap = obs.snapshot()
    bal = snap["gauges"]["rb_shard_balance"]
    assert any(row["labels"].get("mesh") == "2x2" and row["value"] >= 1.0
               for row in bal)
    launches = snap["counters"]["rb_sharded_launches_total"]
    assert any(row["labels"].get("mesh") == "2x2" and row["value"] >= 1
               for row in launches)


def test_shadow_check_catches_silent_corruption(engines, pool):
    from roaringbitmap_tpu.runtime import errors

    eng = ShardedBatchEngine(engines, mesh=_mesh(2))
    policy = guard.GuardPolicy(shadow_rate=1.0)
    eng.execute(pool, policy=policy)          # clean full-rate shadow
    with faults.inject("silent@sharded_engine=1.0:3"):
        with pytest.raises(errors.ShadowMismatch):
            eng.execute(pool, policy=policy)


def test_validation_and_empty(engines):
    eng = ShardedBatchEngine(engines, mesh=_mesh(2))
    with pytest.raises(IndexError):
        eng.execute([BatchGroup(9, [BatchQuery("or", (0, 1))])])
    assert eng.execute([]) == []
    assert eng.execute([BatchGroup(0, [])]) == [[]]
    with pytest.raises(ValueError):
        ShardedBatchEngine(engines, mesh=_mesh(2), placement="bogus")
    with pytest.raises(ValueError):
        # a 3-device row axis cannot run the XOR-paired butterfly
        devs = np.array(jax.devices()[:3]).reshape(3, 1)
        ShardedBatchEngine(engines,
                           mesh=Mesh(devs, ("rows", "data")))
    with pytest.raises(ValueError):
        # missing the data axis entirely
        devs = np.array(jax.devices()[:2]).reshape(2, 1)
        ShardedBatchEngine(engines, mesh=Mesh(devs, ("rows", "lanes")))


# ------------------------------------------------ warmup + compile cache

def test_warmup_precompiles_and_execute_cache_hits(engines):
    """warmup(rungs) compiles the programs a matching execute then
    cache-hits: no new program entries, and the plan cache serves the
    exact warmed pool."""
    eng = ShardedBatchEngine(engines, mesh=_mesh(2))
    rep = eng.warmup(rungs=(2, 4))
    assert rep["programs"] and rep["mesh"] == [2, 1]
    n_programs = len(eng._programs)
    hits0 = eng._programs.stats()["hits"]
    # the exact rung-2 pool warmup built
    pool = [BatchGroup(sid, e._rung_queries(2,
                       ("or", "and", "xor", "andnot")))
            for sid, e in enumerate(eng._engines)]
    eng.execute(pool)
    assert len(eng._programs) == n_programs
    assert eng._programs.stats()["hits"] > hits0


def test_batch_and_multiset_warmup(engines, tenant_bitmaps):
    """The single-set and multiset engines grew the same API: programs
    compile at warmup, the matching execute hits the program cache."""
    be = BatchEngine.from_bitmaps(tenant_bitmaps[0], layout="dense")
    rep = be.warmup(rungs=(2,), ops=("or", "xor"))
    assert rep["programs"]
    n = len(be._programs)
    be.execute(be._rung_queries(2, ("or", "xor")))
    assert len(be._programs) == n
    ms = MultiSetBatchEngine(engines)
    rep = ms.warmup(rungs=(2,))
    assert rep["programs"]
    n = len(ms._programs)
    pool = [BatchGroup(sid, e._rung_queries(2,
                       ("or", "and", "xor", "andnot")))
            for sid, e in enumerate(ms._engines)]
    ms.execute(pool, engine="auto")
    assert len(ms._programs) == n


def test_compile_cache_env_knob(engines, tmp_path, monkeypatch):
    """ROARING_TPU_COMPILE_CACHE points JAX's persistent compilation
    cache at the directory (the env half of ROADMAP item 3)."""
    cache_dir = str(tmp_path / "xla_cache")
    monkeypatch.setattr(rt_warmup, "_applied", (None, None))
    monkeypatch.setenv(rt_warmup.ENV_COMPILE_CACHE, cache_dir)
    eng = ShardedBatchEngine(engines[0], mesh=_mesh(2))
    import jax as _jax

    assert _jax.config.jax_compilation_cache_dir == \
        rt_warmup.compile_cache_dir()
    assert rt_warmup.compile_cache_dir().endswith("xla_cache")
    rep = eng.warmup(rungs=(2,))
    assert rep["compile_cache_dir"] == rt_warmup.compile_cache_dir()
    # unset -> no-op, the applied dir survives (idempotent knob)
    monkeypatch.delenv(rt_warmup.ENV_COMPILE_CACHE)
    assert rt_warmup.enable_compile_cache() is None


def test_spec_layout_vocabulary():
    """The frozen SpecLayout vocabulary the three plan paths share."""
    sp = SpecLayout()
    assert sp.row_axis == "rows" and sp.data_axis == "data" \
        and sp.lane_axis == "lanes"
    assert sp.pooled_rows() == P("rows", None)
    assert sp.gather_rows() == P(("rows", "data"), None)
    assert sp.gather_vec() == P(("rows", "data"))
    assert sp.packed_rows() == P("rows", "lanes")
    assert sp.combined_heads() == P(None, None)
    assert sp.heads() == P(None, "lanes")
    with pytest.raises(dataclasses.FrozenInstanceError):
        sp.row_axis = "x"


def test_predict_sharded_dispatch_bytes_model():
    sigs = [("or", 4, 8, 2, 2, False)]
    one = insights.predict_sharded_dispatch_bytes(sigs, 100, 1, 1)
    four = insights.predict_sharded_dispatch_bytes(sigs, 100, 4, 4)
    # sharded parts divide by D, replicated parts do not
    assert four["per_shard_bytes"] < one["per_shard_bytes"]
    assert four["gather_bytes"] == one["gather_bytes"]
    shard_part = four["gather_bytes"] + four["scratch_bytes"]
    repl_part = four["heads_bytes"] + four["output_bytes"]
    assert four["per_shard_bytes"] == -(-shard_part // 4) + repl_part
    assert four["peak_bytes"] == shard_part + 4 * repl_part
    assert four["resident_per_shard_bytes"] == \
        insights.dense_rows_bytes(25)


# ---------------------------------------------------- CPU-proxy acceptance

@pytest.mark.slow
def test_warm_boot_first_query_near_steady_state():
    """Acceptance (ROADMAP item 3 half): after warmup(rungs) a process's
    first real execute pays no compile — within 10x of the steady-state
    wall (it IS a plan+program cache hit)."""
    import time

    rng = np.random.default_rng(5)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 16, 500).astype(np.uint32))
        for _ in range(8)]
    eng = ShardedBatchEngine(BatchEngine.from_bitmaps(bms,
                                                      layout="dense"),
                             mesh=_mesh(2))
    qs = eng._engines[0]._rung_queries(4, ("or", "and", "xor", "andnot"))
    eng.warmup(pools=[[BatchGroup(0, qs)]])
    t0 = time.perf_counter()
    eng.execute([BatchGroup(0, qs)])
    first = time.perf_counter() - t0
    steady = min(_timed(lambda: eng.execute([BatchGroup(0, qs)]))
                 for _ in range(5))
    assert first <= 10 * steady + 0.05, (first, steady)


def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
