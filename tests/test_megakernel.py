"""One-kernel hot path acceptance (ISSUE 11).

Pins:
- megakernel parity matrix — (op mix x dense/compact/counts layout x
  Batch/MultiSet/Sharded-2x2-mesh) — bit-exact vs the host sequential
  evaluator (``expr.evaluate_host``), including mixed flat + expression
  pools and ad-hoc leaves;
- the demotion ladder: ``ROARING_TPU_FAULTS`` forced lowering faults
  land megakernel -> pallas -> xla, every rung bit-exact;
- the HBM-budget proactive split property ON the megakernel rung;
- ``warmup(rungs=("expr:N",))`` pre-compiles the megakernel rung so a
  matching ``engine="megakernel"`` execute cache-hits;
- the footprint model: the megakernel lowering's predicted transient
  bytes drop >= 2x vs the multi-op lowering at identical plans (the
  acceptance referee where XLA's cost_analysis under-reports pallas
  programs), and ``obs.cost.record_dispatch`` falls back to the model
  estimate with ``estimated=True`` instead of a meaningless roofline;
- the ``expr.megakernel`` span event schema at every dispatch site.
"""

import json

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.obs import cost as obs_cost
from roaringbitmap_tpu.ops import megakernel
from roaringbitmap_tpu.parallel import (BatchEngine, BatchGroup, BatchQuery,
                                        MultiSetBatchEngine,
                                        ShardedBatchEngine)
from roaringbitmap_tpu.parallel import expr
from roaringbitmap_tpu.parallel.batch_engine import resolve_query_engine
from roaringbitmap_tpu.runtime import faults, guard


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()
    # engines built per test sit in reference cycles (compiled-program
    # run closures capture the engine), so their resident-set ledger
    # registrations would otherwise linger until an arbitrary later GC
    # and skew the ledger baselines test_memory_obs samples
    import gc

    gc.collect()


@pytest.fixture(scope="module")
def bitmaps():
    rng = np.random.default_rng(0x11E9)
    out = []
    for i in range(8):
        vals = [rng.integers(0, 1 << 17, 2000).astype(np.uint32)]
        if i % 3 == 0:
            vals.append(np.arange(1 << 16, (1 << 16) + 5000,
                                  dtype=np.uint32))
        out.append(RoaringBitmap.from_values(
            np.unique(np.concatenate(vals))))
    return out


DEPTH2 = expr.and_(expr.or_(0, 1), expr.not_(2))
DEPTH3 = expr.xor(expr.and_(expr.or_(0, 1), expr.or_(2, 3)),
                  expr.andnot(expr.or_(4, 5), 6))


def _pool(form="bitmap"):
    return ([expr.ExprQuery(DEPTH2, form=form),
             expr.ExprQuery(DEPTH3, form=form),
             BatchQuery("xor", (1, 4), form=form),
             BatchQuery("and", (0, 3, 6), form=form),
             BatchQuery("andnot", (2, 5, 7), form=form),
             expr.ExprQuery(DEPTH2)]     # cardinality-only root
            + expr.random_expr_pool(8, 5, depth=2, seed=19, form=form))


def _want(pool, bitmaps):
    out = []
    for q in pool:
        if isinstance(q, expr.ExprQuery):
            out.append(expr.evaluate_host(q.expr, bitmaps))
        else:
            out.append(BatchEngine.from_bitmaps(
                bitmaps, layout="dense")._sequential_one(q))
    return out


def _assert_parity(got, want, pool, tag):
    for i, (g, w) in enumerate(zip(got, want)):
        assert g.cardinality == w.cardinality, (tag, i)
        if pool[i].form == "bitmap":
            assert g.bitmap == w, (tag, i)


# ------------------------------------------------------- parity matrix

@pytest.mark.parametrize("layout", ["dense", "compact", "counts"])
def test_batch_megakernel_parity(bitmaps, layout):
    """(op x layout) parity on the single-set engine: the whole fused
    pipeline in ONE pallas grid kernel, bit-exact vs the host."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout=layout)
    pool = _pool()
    want = _want(pool, bitmaps)
    got = eng.execute(pool, engine="megakernel", fallback=False)
    _assert_parity(got, want, pool, layout)
    plan = eng.plan(pool)
    assert plan.mega is not None and plan.mega.mode == "full"
    assert eng._bucket_engine(plan, "megakernel") == "megakernel"


def test_batch_megakernel_adhoc_leaves(bitmaps):
    rng = np.random.default_rng(3)
    ad = RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 2500).astype(np.uint32)))
    e = expr.xor(expr.and_(expr.or_(0, 1), expr.bitmap(ad)),
                 expr.andnot(expr.bitmap(ad), 2))
    q = expr.ExprQuery(e, form="bitmap")
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    [got] = eng.execute([q], engine="megakernel", fallback=False)
    want = expr.evaluate_host(e, bitmaps)
    assert got.cardinality == want.cardinality and got.bitmap == want


def test_multiset_megakernel_parity():
    rng = np.random.default_rng(0x11EA)
    tenants = [[RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(6)] for _ in range(3)]
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    pool = [BatchGroup(sid, [
        expr.ExprQuery(DEPTH2, form="bitmap"),
        BatchQuery("xor", (1, 3), form="bitmap"),
        expr.ExprQuery(expr.xor(expr.or_(2, 3), expr.and_(4, 5)),
                       form="bitmap")]) for sid in range(3)]
    got = eng.execute(pool, engine="megakernel", fallback=False)
    for sid, rows in enumerate(got):
        srcs = tenants[sid]
        assert rows[0].bitmap == expr.evaluate_host(DEPTH2, srcs), sid
        assert rows[1].bitmap == (srcs[1] ^ srcs[3]), sid
        assert rows[2].bitmap == expr.evaluate_host(
            expr.xor(expr.or_(2, 3), expr.and_(4, 5)), srcs), sid


def test_sharded_mesh_megakernel_combines():
    """The mesh composition: combine passes run as ONE kernel on the
    replicated post-butterfly side (mode="combine"), bit-exact on a 2x2
    mesh for sharded AND replicated placement."""
    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(0x11EB)
    tenants = [[RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(7)] for _ in range(3)]
    pool = [BatchGroup(sid, [
        expr.ExprQuery(DEPTH2, form="bitmap"),
        expr.ExprQuery(DEPTH3),
        BatchQuery("andnot", (0, 1, 3), form="bitmap")])
        for sid in range(3)]
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("rows", "data"))
    for placement in ("replicated", "sharded"):
        sh = ShardedBatchEngine.from_bitmap_sets(
            tenants, mesh=mesh, layout="dense")
        sh2 = ShardedBatchEngine(sh._engines, mesh=mesh,
                                 placement=placement)
        got = sh2.execute(pool, fallback=False)
        plan = sh2._plan(tuple(sh2._single._flatten(pool)[0]))
        assert plan.mega is not None and plan.mega.mode == "combine", \
            placement
        for sid, rows in enumerate(got):
            srcs = tenants[sid]
            assert rows[0].bitmap == expr.evaluate_host(DEPTH2, srcs), \
                (placement, sid)
            assert rows[1].cardinality == expr.evaluate_host(
                DEPTH3, srcs).cardinality, (placement, sid)
            want = srcs[0].clone() - srcs[1] - srcs[3]
            assert rows[2].bitmap == want, (placement, sid)


# -------------------------------------------------- demotion ladder

def test_forced_demotion_megakernel_pallas_xla(bitmaps):
    """Injected lowering faults walk megakernel -> pallas -> xla, every
    landing bit-exact (the ISSUE acceptance ladder pin)."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    pool = _pool()
    want = _want(pool, bitmaps)
    cases = [
        ("lowering@megakernel=1.0:0x11", "pallas"),
        ("lowering@megakernel=1.0,lowering@pallas=1.0:0x12", "xla"),
    ]
    for spec, landing in cases:
        guard.reset_dispatch_stats()
        with faults.inject(spec):
            got = eng.execute(pool, engine="megakernel")
        _assert_parity(got, want, pool, spec)
        stats = guard.dispatch_stats("batch_engine")
        assert stats["demotions"] >= (1 if landing == "pallas" else 2), \
            (spec, stats)
    # every device rung dead: the sequential floor still answers
    with faults.inject("lowering=1.0:0x13"):
        got = eng.execute(pool, engine="megakernel")
    _assert_parity(got, want, pool, "floor")


def test_unfit_plans_resolve_to_pallas(bitmaps, monkeypatch):
    """A plan with no fused sections — or one past the VMEM/SMEM budget
    — resolves the megakernel rung down to pallas silently."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    flat = [BatchQuery("or", (0, 1)), BatchQuery("xor", (2, 3))]
    plan = eng.plan(flat)
    assert plan.mega is None
    assert eng._bucket_engine(plan, "megakernel") == "pallas"
    got = eng.execute(flat, engine="megakernel", fallback=False)
    want = _want(flat, bitmaps)
    _assert_parity(got, want, flat, "flat")
    # budget squeeze: force fits() False via the slot ceiling
    pool = _pool()
    eplan = eng.plan(pool)
    monkeypatch.setattr(megakernel, "MAX_SLOTS", 1)
    assert not eplan.mega.fits()
    assert eng._bucket_engine(eplan, "megakernel") == "pallas"


def test_auto_resolution_rules(bitmaps):
    """On the CPU proxy auto stays xla (unchanged default); explicit
    megakernel always starts the chain at the top rung."""
    pool = _pool()
    assert resolve_query_engine("auto", pool) == "xla"
    assert resolve_query_engine("megakernel", pool) == "megakernel"
    assert resolve_query_engine("pallas", pool) == "pallas"
    chain = guard.chain_from(
        resolve_query_engine("megakernel", pool),
        ("megakernel", "pallas", "xla", "xla-vmap"))
    assert chain == ("megakernel", "pallas", "xla", "xla-vmap",
                     "sequential")


# ------------------------------------------------ budget + bytes model

def test_budget_splits_megakernel_batches(bitmaps, tmp_path):
    """Property: ROARING_TPU_HBM_BUDGET proactively splits megakernel
    batches BEFORE dispatch, every dispatched launch's prediction fits
    the budget, bit-exact."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    pool = expr.random_expr_pool(8, 12, depth=2, seed=29, form="bitmap")
    want = [expr.evaluate_host(q.expr, bitmaps) for q in pool]
    full = eng.predict_dispatch_bytes(pool, engine="megakernel")
    budget = max(1, full // 3)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    got = eng.execute(pool, engine="megakernel",
                      policy=guard.GuardPolicy(hbm_budget=budget))
    obs.disable()
    assert [g.bitmap for g in got] == want
    assert eng.proactive_split_count > 0
    spans = [json.loads(line) for line in open(path)]
    mems = [ev for s in spans if s["name"] == "batch.dispatch"
            for ev in s["events"] if ev["name"] == "batch.memory"]
    assert mems and all(ev["predicted_bytes"] <= budget for ev in mems)
    megas = [ev for s in spans if s["name"] == "batch.dispatch"
             for ev in s["events"] if ev["name"] == "expr.megakernel"]
    assert megas and all(ev["mode"] == "full" and ev["steps"] > 0
                         and ev["slots"] > 0 and ev["vmem_bytes"] > 0
                         for ev in megas)


def test_bytes_model_2x_drop(bitmaps):
    """THE acceptance referee: predicted transient bytes per fused
    expression batch drop >= 2x under the megakernel lowering vs the
    multi-op pallas AND xla lowerings of the IDENTICAL plan."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    pool = [q for q in _pool() if isinstance(q, expr.ExprQuery)]
    plan = eng.plan(pool)
    b_sigs = [b.signature for b in plan]
    by_eng = {}
    for e in ("megakernel", "pallas", "xla"):
        total = insights.predict_batch_dispatch_bytes(
            b_sigs, "dense", 0, e)["peak_bytes"]
        total += insights.predict_expr_dispatch_bytes(
            plan.expr_signature, e)["peak_bytes"]
        by_eng[e] = total
    assert by_eng["pallas"] >= 2 * by_eng["megakernel"], by_eng
    assert by_eng["xla"] >= 2 * by_eng["megakernel"], by_eng


def test_roofline_estimated_fallback():
    """obs.cost satellite: when cost_analysis is missing or reports no
    bytes (legal for pallas_call programs), the model estimate backs
    the roofline gauge and the event is flagged estimated=True."""
    obs.reset()
    doc = obs_cost.record_dispatch(
        "t_mega", "megakernel", None, 0.01,
        est={"flops": 1e6, "bytes_accessed": 2e6})
    assert doc["estimated"] is True
    assert doc["bytes_accessed"] == 2e6
    assert 0 < doc["roofline_fraction"] <= 1.0
    doc = obs_cost.record_dispatch(
        "t_mega", "megakernel",
        {"flops": 5.0, "bytes_accessed": 0.0, "transcendentals": 0.0},
        0.01, est={"flops": 1e6, "bytes_accessed": 2e6})
    assert doc["estimated"] is True and doc["bytes_accessed"] == 2e6
    # a real analysis is never overridden
    doc = obs_cost.record_dispatch(
        "t_mega", "xla",
        {"flops": 5.0, "bytes_accessed": 7.0, "transcendentals": 0.0},
        0.01, est={"flops": 1e6, "bytes_accessed": 2e6})
    assert "estimated" not in doc and doc["bytes_accessed"] == 7.0


def test_dispatch_cost_event_carries_bytes(bitmaps):
    """Every megakernel dispatch reports a usable bytes_accessed figure
    (real or flagged estimate) — the gauge the bench lane's
    mega_vs_multiop_x cell reads."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    pool = _pool("cardinality")
    eng.execute(pool, engine="megakernel", fallback=False)
    ev = eng.last_dispatch_cost
    assert ev["bytes_accessed"] > 0
    assert 0 < ev["roofline_fraction"] <= 1.0


# ----------------------------------------------------------- warmup

def test_warmup_precompiles_megakernel_rung(bitmaps):
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    rep = eng.warmup(rungs=("expr:2",))
    assert any(p["engine"] == "megakernel" for p in rep["programs"])
    hits0 = eng._programs.stats()["hits"]
    n0 = len(eng._programs)
    got = eng.execute(expr.rung_expressions(2, eng.n),
                      engine="megakernel")
    assert len(got) == len(expr.rung_expressions(2, eng.n))
    assert len(eng._programs) == n0          # nothing new compiled
    assert eng._programs.stats()["hits"] > hits0


def test_multiset_warmup_precompiles_megakernel_rung():
    rng = np.random.default_rng(0x11EC)
    tenants = [[RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 16, 800).astype(np.uint32)))
        for _ in range(4)] for _ in range(2)]
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    rep = eng.warmup(rungs=("expr:2",))
    assert any(p["engine"] == "megakernel" for p in rep["programs"])


# ------------------------------------------------------ cache hygiene

def test_program_cache_keys_on_instruction_shape(bitmaps):
    """Two plans sharing padded bucket signatures but different real
    row counts must compile DIFFERENT megakernel programs (the
    instruction stream is plan data, not bucket shape)."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    p1 = [expr.ExprQuery(expr.and_(expr.or_(0, 1), expr.not_(2)))]
    p2 = [expr.ExprQuery(expr.and_(expr.or_(0, 3), expr.not_(5)))]
    plan1, plan2 = eng.plan(p1), eng.plan(p2)
    n0 = len(eng._programs)
    eng.execute(p1, engine="megakernel", fallback=False)
    n1 = len(eng._programs)
    eng.execute(p2, engine="megakernel", fallback=False)
    n2 = len(eng._programs)
    assert n1 > n0
    if plan1.mega.signature != plan2.mega.signature:
        assert n2 > n1
    else:
        assert n2 == n1      # identical shapes legitimately share
