"""Device-native analytics lane acceptance (ISSUE 15): BSI +
RangeBitmap value queries as first-class engine ops fused with the
expression DAG (roaringbitmap_tpu.analytics, docs/ANALYTICS.md).

Pins:
- predicate parity matrix: every cmp/range op x column kind x engine
  rung is bit-exact vs the host BSI / RangeBitmap oracle, composed
  with set algebra (filter-then-aggregate in ONE launch);
- aggregate roots: ``sum_`` (total + count) and ``top_k`` (clamping +
  smallest-id tie trim) vs the host oracle, on Batch / MultiSet /
  Sharded, including fault-injected demotion down to the sequential
  oracle floor;
- the HBM ledger: columns AND the parity-tier DeviceBSI /
  DeviceRangeBitmap register resident bytes with GC-release finalizers;
- the result cache: analytics keys carry column ``(uid, version)``
  leaves, hits serve aggregate values, and ``apply_delta`` on a column
  invalidates exactly its dependent entries;
- the property stream (the PR 12 mutation-stream mirror): N interleaved
  column-delta / analytics-query steps stay bit-exact vs the host
  oracle under ``ROARING_TPU_FAULTS``;
- the lattice: ``bsi=<depth>`` profile rungs round-trip, warmed
  analytics traffic replaying NEW predicate values compiles nothing,
  and an unwarmed depth escapes typed (in_vocabulary=False);
- serving-loop admission: analytics ExprQuerys ride the one-wire-shape
  contract unchanged (bitmap->cardinality degrade included).
"""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.analytics import (BsiColumn, RangeColumn,
                                         two_phase_execute)
from roaringbitmap_tpu.mutation import ResultCache
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.parallel import expr
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchEngine, BatchQuery
from roaringbitmap_tpu.parallel.multiset import (BatchGroup,
                                                 MultiSetBatchEngine)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.runtime import lattice as rt_lattice


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    rt_lattice.deactivate()
    yield
    obs.disable()
    obs.reset()
    rt_lattice.deactivate()


def mk_bitmaps(seed, n=4, uni=1 << 17, card=2000):
    rng = np.random.default_rng(seed)
    return [RoaringBitmap.from_values(
        np.unique(rng.integers(0, uni, card)).astype(np.uint32))
        for _ in range(n)]


def mk_bsi_col(seed, name="price", uni=1 << 17, n=5000, vmax=9000):
    rng = np.random.default_rng(seed)
    ids = np.unique(rng.integers(0, uni, n)).astype(np.uint32)
    vals = rng.integers(0, vmax, ids.size).astype(np.int64)
    return BsiColumn(name, ids, vals)


def mk_range_col(seed, name="lat", rows=3000, vmax=1 << 40):
    rng = np.random.default_rng(seed)
    return RangeColumn(name,
                       rng.integers(0, vmax, rows).astype(np.int64))


def build(seed=11, col_seed=12, layout="auto"):
    bms = mk_bitmaps(seed)
    ds = DeviceBitmapSet(bms, layout=layout)
    col = mk_bsi_col(col_seed)
    ds.attach_column(col)
    return bms, ds, col


# ----------------------------------------------------------- predicates

@pytest.mark.parametrize("engine", ["xla", "xla-vmap", "pallas"])
@pytest.mark.parametrize("op,args", [
    ("range", (150, 6200)), ("eq", None), ("neq", None),
    ("lt", (4000,)), ("le", (4000,)), ("gt", (700,)), ("ge", (700,)),
])
def test_predicate_parity_bsi(engine, op, args):
    bms, ds, col = build()
    eng = BatchEngine(ds, result_cache=None)
    if args is None:        # a stored value, so eq/neq are non-trivial
        v, ok = col.host.get_value(int(col.host.ebm.to_array()[7]))
        assert ok
        args = (v,)
    pred = (expr.range_("price", *args) if op == "range"
            else expr.cmp("price", op, args[0]))
    q = expr.ExprQuery(expr.and_(expr.or_(0, 1), pred), form="bitmap")
    got = eng.execute([q], engine=engine, fallback=False)[0]
    ref = expr.evaluate_host(q.expr, bms, {"price": col})
    assert got.bitmap == ref, (op, engine)
    assert got.cardinality == ref.cardinality


@pytest.mark.parametrize("op,lo,hi", [
    ("range", 1 << 30, 1 << 39), ("le", 1 << 38, 0), ("ge", 1 << 38, 0),
    ("lt", 1 << 38, 0), ("gt", 1 << 38, 0),
])
def test_predicate_parity_range_column(op, lo, hi):
    """64-bit value domains ride the RangeBitmap threshold family."""
    rng = np.random.default_rng(5)
    rc = mk_range_col(6)
    bms = [RoaringBitmap.from_values(np.unique(
        rng.integers(0, 3000, 900)).astype(np.uint32)) for _ in range(3)]
    ds = DeviceBitmapSet(bms)
    ds.attach_column(rc)
    eng = BatchEngine(ds, result_cache=None)
    pred = (expr.range_("lat", lo, hi) if op == "range"
            else expr.cmp("lat", op, lo))
    q = expr.ExprQuery(expr.andnot(pred, expr.ref(2)), form="bitmap")
    got = eng.execute([q])[0]
    ref = expr.evaluate_host(q.expr, bms, {"lat": rc})
    assert got.bitmap == ref, op


def test_pruned_predicates_skip_device():
    """Min/max pruning answers all/empty without a scan — same rule as
    the host comparator, so parity holds at the guard values too."""
    bms, ds, col = build()
    eng = BatchEngine(ds, result_cache=None)
    for pred in (expr.cmp("price", "ge", 0),              # all
                 expr.cmp("price", "gt", col.max_value),  # empty
                 expr.range_("price", -5, col.max_value + 7)):  # all
        q = expr.ExprQuery(pred, form="bitmap")
        got = eng.execute([q])[0]
        assert got.bitmap == expr.evaluate_host(pred, bms,
                                                {"price": col})


def test_out_of_band_neq_matches_all_rows_both_tiers():
    """NEQ with a predicate outside [min, max] matches EVERY stored row
    on both tiers: the shared minmax pruning answers "all" before
    either scan runs.  Regression — the host O'Neil scan used to
    truncate the predicate to bit_count bits (8 -> 0 over a 3-bit
    column) and drop the rows whose value equals the alias, while the
    padded device scan decomposed it exactly."""
    ids = np.array([1, 2, 3], np.uint32)
    col = BsiColumn("price", ids, np.array([0, 5, 2], np.int64))
    assert (col.depth, col.min_value, col.max_value) == (3, 0, 5)
    ds = DeviceBitmapSet([RoaringBitmap.from_values(ids)],
                         layout="dense")
    ds.attach_column(col)
    eng = BatchEngine(ds, result_cache=None)
    every = RoaringBitmap.from_values(ids)
    for v in (8, col.max_value + 1, -3):   # out-of-band incl. the alias
        assert col.scan_plan("neq", v) == ("all",)
        pred = expr.cmp("price", "neq", v)
        got = eng.execute([expr.ExprQuery(pred, form="bitmap")])[0]
        host = expr.evaluate_host(pred, [every], {"price": col})
        assert got.bitmap == host == every, v
    # in-band NEQ still scans (a stored value: non-trivial result)
    assert col.scan_plan("neq", 2)[0] == "scan"


def test_expr_node_report_reconciles_with_section_predictor():
    """Summing the per-node EXPLAIN 'est_bytes' rows reproduces the
    section-level predict_expr_dispatch_bytes total — for aggregate
    (vagg) roots too, whose compact output lives in their own row."""
    from roaringbitmap_tpu.insights import analysis as insights
    bms, ds, col = build(131, 132)
    eng = BatchEngine(ds, result_cache=None)
    found = expr.and_(expr.or_(0, 1), expr.range_("price", 10, 800))
    for q in (expr.ExprQuery(expr.sum_("price", found=found)),
              expr.ExprQuery(expr.top_k("price", 4, found=found),
                             form="bitmap"),
              expr.ExprQuery(found, form="bitmap")):
        plan = eng.plan([q])
        for sig in plan.expr_signature:
            per_node = sum(r["est_bytes"]
                           for r in insights.expr_node_report(sig))
            section = insights.predict_expr_dispatch_bytes(
                [sig], "xla")["peak_bytes"]
            assert per_node == section, (q, sig[0])


# ----------------------------------------------------------- aggregates

def test_sum_fused_parity_and_value():
    bms, ds, col = build()
    eng = BatchEngine(ds, result_cache=None)
    found = expr.and_(expr.or_(0, 1),
                      expr.range_("price", 100, 5000))
    q = expr.ExprQuery(expr.sum_("price", found=found))
    got = eng.execute([q])[0]
    card, value, _ = expr.evaluate_host_agg(q.expr, bms,
                                            {"price": col})
    assert (got.cardinality, got.value) == (card, value)
    # found=None sums the whole stored domain
    q2 = expr.ExprQuery(expr.sum_("price"))
    got2 = eng.execute([q2])[0]
    total, count = col.host_sum(None)
    assert (got2.cardinality, got2.value) == (count, total)


def test_top_k_parity_clamp_and_ties():
    bms, ds, col = build()
    eng = BatchEngine(ds, result_cache=None)
    found = expr.or_(0, 1, 2)
    for k in (1, 13, 10 ** 7):      # huge k clamps to the found count
        q = expr.ExprQuery(expr.top_k("price", k, found=found),
                           form="bitmap")
        got = eng.execute([q])[0]
        card, _, bm = expr.evaluate_host_agg(q.expr, bms,
                                             {"price": col})
        assert got.bitmap == bm, k
        assert got.cardinality == card


def test_sum_rejects_bitmap_form_and_nested_agg():
    with pytest.raises(ValueError):
        expr.ExprQuery(expr.sum_("price"), form="bitmap")
    with pytest.raises(ValueError):
        expr.canonicalize(expr.or_(expr.sum_("price"), expr.ref(0)))


def test_missing_column_raises_typed():
    bms, ds, _ = build()
    eng = BatchEngine(ds, result_cache=None)
    with pytest.raises(KeyError):
        eng.execute([expr.ExprQuery(expr.cmp("nope", "le", 3))])


# ------------------------------------------------- engines / demotion

def _mk_two_tenants():
    bms_a = mk_bitmaps(21, uni=1 << 16, card=1500)
    bms_b = mk_bitmaps(22, uni=1 << 15, card=1200)
    ds_a, ds_b = DeviceBitmapSet(bms_a), DeviceBitmapSet(bms_b)
    col_a = mk_bsi_col(23, uni=1 << 16, vmax=5000)
    col_b = mk_bsi_col(24, uni=1 << 15, vmax=800)
    ds_a.attach_column(col_a)
    ds_b.attach_column(col_b)
    qa = expr.ExprQuery(expr.sum_(
        "price", found=expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 10, 3000))))
    qb = expr.ExprQuery(expr.and_(expr.ref(2),
                                  expr.cmp("price", "ge", 300)),
                        form="bitmap")
    return (bms_a, ds_a, col_a), (bms_b, ds_b, col_b), qa, qb


def _assert_pooled_exact(out, tenants, qa, qb):
    for sid, (bms_x, _ds, col_x) in enumerate(tenants):
        card, value, _ = expr.evaluate_host_agg(qa.expr, bms_x,
                                                {"price": col_x})
        assert (out[sid][0].cardinality, out[sid][0].value) \
            == (card, value), f"sum tenant {sid}"
        ref = expr.evaluate_host(qb.expr, bms_x, {"price": col_x})
        assert out[sid][1].bitmap == ref, f"filter tenant {sid}"


def test_multiset_pooled_analytics_parity():
    a, b, qa, qb = _mk_two_tenants()
    ms = MultiSetBatchEngine([a[1], b[1]])
    out = ms.execute([BatchGroup(0, [qa, qb]), BatchGroup(1, [qa, qb])])
    _assert_pooled_exact(out, (a, b), qa, qb)


def test_sharded_analytics_parity():
    from roaringbitmap_tpu.parallel.sharded_engine import \
        ShardedBatchEngine

    a, b, qa, qb = _mk_two_tenants()
    sh = ShardedBatchEngine([a[1], b[1]])
    out = sh.execute([BatchGroup(0, [qa, qb]),
                      BatchGroup(1, [qa, qb])])
    _assert_pooled_exact(out, (a, b), qa, qb)


def test_sharded_column_delta_replaces_planes():
    """A VALUE-ONLY column delta (stable shapes: structure_version
    unchanged) must re-place the sharded engine's replicated slice
    planes — a (uid, structure_version)-keyed upload cache would serve
    the pre-delta planes and diverge from the host oracle."""
    from roaringbitmap_tpu.parallel.sharded_engine import \
        ShardedBatchEngine

    a, b, qa, qb = _mk_two_tenants()
    sh = ShardedBatchEngine([a[1], b[1]])
    # the whole-domain sum makes ANY stale plane visible: every stored
    # value rides the vagg contraction, so a one-row patch moves it
    qs = expr.ExprQuery(expr.sum_("price"))
    pool = [BatchGroup(0, [qa, qb, qs]), BatchGroup(1, [qa, qb, qs])]
    sh.execute(pool)                     # planes now upload-cached
    for _bms, _ds, col in (a, b):
        rid = int(col.host.ebm.to_array()[0])
        v, ok = col.host.get_value(rid)
        assert ok
        s0 = col.structure_version
        col.apply_delta(set_values={rid: (int(v) + 1) % 4000})
        assert col.structure_version == s0, \
            "value-only patch must keep shapes (else this test " \
            "stops covering the stale-plane path)"
    out = sh.execute(pool)
    _assert_pooled_exact(out, (a, b), qa, qb)
    for sid, (_bms, _ds, col) in enumerate((a, b)):
        total, count = col.host_sum(None)
        assert (out[sid][2].cardinality, out[sid][2].value) \
            == (count, total), f"stale whole-domain sum tenant {sid}"


@pytest.mark.parametrize("fault_spec", [
    "lowering@batch_engine=1.0:77",        # demote to the floor
    "transient@batch_engine=0.5:1234",     # retries along the way
])
def test_fault_demotion_bit_exact(fault_spec):
    bms, ds, col = build(31, 32)
    eng = BatchEngine(ds, result_cache=None)
    q1 = expr.ExprQuery(expr.sum_(
        "price", found=expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 50, 4000))))
    q2 = expr.ExprQuery(expr.and_(expr.ref(0),
                                  expr.cmp("price", "le", 2500)),
                        form="bitmap")
    with faults.inject(fault_spec):
        out = eng.execute([q1, q2])
    card, value, _ = expr.evaluate_host_agg(q1.expr, bms,
                                            {"price": col})
    assert (out[0].cardinality, out[0].value) == (card, value)
    assert out[1].bitmap == expr.evaluate_host(q2.expr, bms,
                                               {"price": col})


def test_two_phase_matches_fused():
    bms, ds, col = build(41, 42)
    eng = BatchEngine(ds, result_cache=None)
    qs = [expr.ExprQuery(expr.sum_(
              "price", found=expr.and_(expr.or_(0, 1),
                                       expr.range_("price", 1, 6000)))),
          expr.ExprQuery(expr.top_k("price", 9, found=expr.ref(0)),
                         form="bitmap")]
    fused = eng.execute(qs)
    tp = two_phase_execute(eng, qs)
    assert (fused[0].cardinality, fused[0].value) \
        == (tp[0].cardinality, tp[0].value)
    assert fused[1].bitmap == tp[1].bitmap


# ------------------------------------------------------------- ledger

def test_columns_and_device_tiers_register_in_ledger():
    base = obs_memory.LEDGER.resident_bytes("bsi_column")
    col = mk_bsi_col(51)
    assert obs_memory.LEDGER.resident_bytes("bsi_column") \
        == base + col.hbm_bytes()
    assert col.hbm_bytes() > 0
    snap = obs.snapshot()["hbm"]["by_kind"]
    assert "bsi_column" in snap

    # the parity-tier device shims register too (the satellite fix)
    from roaringbitmap_tpu.bsi.device import (DeviceBSI,
                                              DeviceRangeBitmap)
    from roaringbitmap_tpu.core.rangebitmap import RangeBitmap

    b0 = obs_memory.LEDGER.resident_bytes("bsi")
    dev = DeviceBSI(col.host)
    assert obs_memory.LEDGER.resident_bytes("bsi") \
        == b0 + dev.hbm_bytes()
    app = RangeBitmap.appender(100)
    for v in (3, 60, 99):
        app.add(v)
    r0 = obs_memory.LEDGER.resident_bytes("rangebitmap")
    drb = DeviceRangeBitmap(app.build())
    assert obs_memory.LEDGER.resident_bytes("rangebitmap") \
        == r0 + drb.hbm_bytes()
    # GC releases through the finalizer
    import gc

    del dev, drb
    gc.collect()
    assert obs_memory.LEDGER.resident_bytes("bsi") == b0
    assert obs_memory.LEDGER.resident_bytes("rangebitmap") == r0


def test_column_delta_updates_ledger():
    base = obs_memory.LEDGER.resident_bytes("bsi_column")
    col = mk_bsi_col(52, n=500)
    assert obs_memory.LEDGER.resident_bytes("bsi_column") \
        == base + col.hbm_bytes()
    v0, s0 = col.version, col.structure_version
    col.apply_delta(set_values={1: 3, 2: 123456})  # deeper slices
    # the in-place update re-sized the SAME registration
    assert obs_memory.LEDGER.resident_bytes("bsi_column") \
        == base + col.hbm_bytes()
    assert col.version == v0 + 1
    assert col.structure_version > s0      # depth/key shapes moved


# --------------------------------------------------------- result cache

def test_result_cache_serves_values_and_column_delta_invalidates():
    bms, ds, col = build(61, 62)
    rc = ResultCache(2 << 20)
    eng = BatchEngine(ds, result_cache=rc)
    q = expr.ExprQuery(expr.sum_(
        "price", found=expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 5, 4000))))
    r1 = eng.execute([q])[0]
    hits0 = rc.hits
    r2 = eng.execute([q])[0]
    assert rc.hits > hits0
    assert (r2.cardinality, r2.value) == (r1.cardinality, r1.value)
    # a SET-only query's entry must survive the COLUMN delta (exact)
    flat = BatchQuery("or", (0, 1))
    eng.execute([flat])
    inv0 = rc.invalidations
    col.apply_delta(set_values={int(col.host.ebm.to_array()[0]): 4321})
    assert rc.invalidations > inv0
    assert rc.would_hit(eng._cache_key_of(flat)[0])     # survived
    r3 = eng.execute([q])[0]
    card, value, _ = expr.evaluate_host_agg(q.expr, bms,
                                            {"price": col})
    assert (r3.cardinality, r3.value) == (card, value)


# ---------------------------------------------- property stream (oracle)

@pytest.mark.parametrize("kind", ["bsi", "range"])
@pytest.mark.parametrize("fault_spec",
                         [None, "transient@batch_engine=0.4:1337"])
def test_property_interleaved_column_delta_query_stream(kind,
                                                        fault_spec):
    """N interleaved apply_delta-on-column / analytics-query steps vs
    the host oracle under ROARING_TPU_FAULTS — the PR 12 mutation
    stream mirrored onto the value domain (satellite 3)."""
    rng = np.random.default_rng(0xB51)
    uni = 1 << 14
    bms = mk_bitmaps(71, n=3, uni=uni, card=900)
    ds = DeviceBitmapSet(bms)
    if kind == "bsi":
        col = mk_bsi_col(72, uni=uni, n=1500, vmax=4000)
    else:
        col = RangeColumn("price",
                          rng.integers(0, 4000, 2048).astype(np.int64))
    ds.attach_column(col)
    eng = BatchEngine(ds, result_cache=ResultCache(2 << 20))
    ctx = faults.inject(fault_spec) if fault_spec else None
    if ctx:
        ctx.__enter__()
    try:
        for step in range(8):
            if step % 2 == 1:
                if kind == "bsi":
                    ids = rng.integers(0, uni, 4)
                    vals = rng.integers(0, 4000, 4)
                    col.apply_delta(set_values={
                        int(i): int(v) for i, v in zip(ids, vals)})
                else:
                    rows = rng.integers(0, 2048, 4)
                    vals = rng.integers(0, 4000, 4)
                    col.apply_delta({int(r): int(v)
                                     for r, v in zip(rows, vals)})
            lo = int(rng.integers(0, 2000))
            hi = lo + int(rng.integers(1, 2000))
            qs = [
                expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                         expr.range_("price", lo, hi)),
                               form="bitmap"),
                expr.ExprQuery(expr.sum_(
                    "price",
                    found=expr.and_(expr.ref(2),
                                    expr.cmp("price", "ge", lo)))),
                expr.ExprQuery(expr.top_k("price", 5,
                                          found=expr.or_(0, 2)),
                               form="bitmap"),
            ]
            got = eng.execute(qs)
            cols = {"price": col}
            ref0 = expr.evaluate_host(qs[0].expr, bms, cols)
            assert got[0].bitmap == ref0, step
            c1, v1, _ = expr.evaluate_host_agg(qs[1].expr, bms, cols)
            assert (got[1].cardinality, got[1].value) == (c1, v1), step
            _, _, bm2 = expr.evaluate_host_agg(qs[2].expr, bms, cols)
            assert got[2].bitmap == bm2, step
    finally:
        if ctx:
            ctx.__exit__(None, None, None)


# ------------------------------------------------------------- lattice

def test_lattice_bsi_profile_round_trip():
    lat = rt_lattice.Lattice.from_profile(
        "q=4,;rows=16;keys=4;ops=or,and;heads=both;bsi=16,")
    assert lat.bsi == (16,)
    assert rt_lattice.Lattice.from_profile(lat.to_profile()) == lat
    assert lat.n_points() == rt_lattice.Lattice.from_profile(
        lat.to_profile()).n_points()


def test_warmed_analytics_traffic_compiles_nothing(monkeypatch):
    # ambient fault injection (the CI fault lane) demotes mid-replay to
    # unwarmed rungs whose compile is legitimate — the zero-compile
    # claim is about clean warmed traffic (test_lattice.py precedent)
    monkeypatch.delenv("ROARING_TPU_FAULTS", raising=False)
    bms, ds, col = build(81, 82)
    eng = BatchEngine(ds, result_cache=None)
    prof = ("q=4,;rows=64;keys=8;ops=or,and,xor,andnot;heads=both;"
            "expr=2;bsi=16,")
    rep = eng.warmup(profile=prof)
    assert rep["lattice"]["sealed"]
    c0 = obs_metrics.compile_miss_total()
    e0 = rt_lattice.escape_total()
    # replay the warmed shapes with NEW predicate values / k each time
    for lo, hi in ((100, 3000), (7, 6000), (1234, 4321)):
        eng.execute([expr.ExprQuery(
            expr.and_(expr.ref(0), expr.range_("price", lo, hi)))])
    for v in (500, 2500, col.max_value, -3):
        eng.execute([expr.ExprQuery(expr.cmp("price", "le", v))])
    eng.execute([expr.ExprQuery(expr.sum_("price",
                                          found=expr.ref(0)))])
    for k in (2, 9):
        eng.execute([expr.ExprQuery(
            expr.top_k("price", k, found=expr.ref(0)), form="bitmap")])
    assert obs_metrics.compile_miss_total() == c0
    assert rt_lattice.escape_total() == e0


def test_unwarmed_analytics_depth_is_out_of_vocabulary_escape(
        monkeypatch):
    monkeypatch.delenv("ROARING_TPU_FAULTS", raising=False)
    bms, ds, col = build(91, 92)
    eng = BatchEngine(ds, result_cache=None)
    # no bsi rungs: analytics traffic is out of vocabulary
    eng.warmup(profile="q=4,;rows=64;keys=8;ops=or,and;heads=both")
    e0 = rt_lattice.escape_total()
    eng.execute([expr.ExprQuery(expr.cmp("price", "le", 100))])
    assert rt_lattice.escape_total() > e0


def test_recommend_lattice_collects_bsi_depths(tmp_path):
    from roaringbitmap_tpu.insights.analysis import recommend_lattice

    bms, ds, col = build(101, 102)
    eng = BatchEngine(ds, result_cache=None)
    trace = tmp_path / "t.jsonl"
    obs.enable(str(trace))
    eng.execute([expr.ExprQuery(
        expr.and_(expr.ref(0), expr.range_("price", 9, 900)))])
    obs.disable()
    rep = recommend_lattice(str(trace))
    assert col.depth_pad in rep["observed"]["bsi_depths"]
    assert f"bsi={col.depth_pad}" in rep["profile"]


# ------------------------------------------------------------- serving

def test_serving_loop_admits_analytics_queries():
    from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                           ServingRequest)

    a, b, qa, qb = _mk_two_tenants()
    ms = MultiSetBatchEngine([a[1], b[1]])
    loop = ServingLoop(ms, ServingPolicy(pool_target=4))
    reqs = [ServingRequest(0, qa), ServingRequest(1, qa),
            ServingRequest(0, qb), ServingRequest(1, qb)]
    tickets = [loop.submit(r) for r in reqs]
    loop.pump(force=True)
    loop.drain()
    assert all(t.status == "done" for t in tickets)
    for t, (sid, q) in zip(tickets, ((0, qa), (1, qa),
                                     (0, qb), (1, qb))):
        ref = ms._engines[sid]._sequential_result(q)
        assert t.result.cardinality == ref.cardinality
        assert t.result.value == ref.value
        if q.form == "bitmap":
            assert t.result.bitmap == ref.bitmap


# ----------------------------------------------------------- obs / plan

def test_analytics_scan_event_and_explain(tmp_path):
    bms, ds, col = build(111, 112)
    eng = BatchEngine(ds, result_cache=None)
    trace = tmp_path / "t.jsonl"
    obs.enable(str(trace))
    q = expr.ExprQuery(expr.sum_(
        "price", found=expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 10, 800))))
    eng.execute([q])
    obs.disable()
    import json

    events = []
    with open(trace) as f:
        for line in f:
            span = json.loads(line)
            events += [ev for ev in span.get("events", [])
                       if ev.get("name") == "analytics.scan"]
    assert events, "no analytics.scan event on the dispatch span"
    ev = events[0]
    assert ev["scans"] >= 1 and ev["aggs"] == 1
    assert ev["bsi_depth"] == col.depth_pad
    # counters moved
    snap = obs.snapshot()["counters"]
    assert any(r["value"] > 0
               for r in snap.get("rb_analytics_scans_total", []))
    # explain() reports the analytics section without dispatching
    rep = eng.explain([q])
    row = rep["exprs"][0]
    assert any(s["kind"] == "vagg" for s in row["per_node"])
    assert rep["predicted"]["peak_bytes"] > 0


def test_megakernel_rung_runs_analytics_in_kernel():
    """Megakernel v2: the one-kernel assembler emits VSCAN steps, so an
    explicit megakernel request STAYS on the top rung for analytics
    plans (pre-v2 it silently resolved down) and answers bit-exactly."""
    bms, ds, col = build(121, 122)
    eng = BatchEngine(ds, result_cache=None)
    q = expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 10, 4000)))
    plan = eng.plan([q])
    assert plan.mega is not None and plan.mega.n_vscan >= 1
    assert eng._bucket_engine(plan, "megakernel") == "megakernel"
    got = eng.execute([q], engine="megakernel", fallback=False)[0]
    ref = expr.evaluate_host(q.expr, bms, {"price": col})
    assert got.cardinality == ref.cardinality


# --------------------------------------- megakernel v2 parity matrix

def _mega_queries():
    """Every analytics root family through one fused pool: predicate
    filters in both forms, sum, and top-k."""
    return [
        expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                 expr.cmp("price", "le", 2500)),
                       form="bitmap"),
        expr.ExprQuery(expr.andnot(expr.range_("price", 100, 5000),
                                   expr.ref(2))),
        expr.ExprQuery(expr.sum_(
            "price", found=expr.and_(expr.or_(0, 1),
                                     expr.range_("price", 50, 4000)))),
        expr.ExprQuery(expr.top_k("price", 7, found=expr.or_(0, 1, 2)),
                       form="bitmap"),
    ]


def _assert_mega_exact(got, qs, bms, col, tag=""):
    for i, (g, q) in enumerate(zip(got, qs)):
        if expr.is_agg(q.expr):
            card, value, bm = expr.evaluate_host_agg(q.expr, bms,
                                                     {"price": col})
            assert (g.cardinality, g.value) == (card, value), (tag, i)
            if q.form == "bitmap":
                assert g.bitmap == bm, (tag, i)
        else:
            ref = expr.evaluate_host(q.expr, bms, {"price": col})
            assert g.cardinality == ref.cardinality, (tag, i)
            if q.form == "bitmap":
                assert g.bitmap == ref, (tag, i)


@pytest.mark.parametrize("layout", ["dense", "compact", "counts"])
def test_megakernel_analytics_parity_batch(layout):
    """Filter-then-aggregate in the ONE-kernel rung (explicit
    engine="megakernel", no fallback), every root family x layout,
    bit-exact vs the host oracle."""
    bms, ds, col = build(61, 62, layout=layout)
    eng = BatchEngine(ds, result_cache=None)
    qs = _mega_queries()
    plan = eng.plan(qs)
    assert plan.mega is not None and plan.mega.fits()
    assert plan.mega.n_vscan >= 1 and plan.mega.n_vagg >= 1
    assert eng._bucket_engine(plan, "megakernel") == "megakernel"
    got = eng.execute(qs, engine="megakernel", fallback=False)
    _assert_mega_exact(got, qs, bms, col, layout)


def _mega_events(trace_path):
    import json

    events = []
    with open(trace_path) as f:
        for line in f:
            events += [ev for ev in json.loads(line).get("events", [])
                       if ev.get("name") == "expr.megakernel"]
    return events


def test_megakernel_analytics_parity_multiset(tmp_path):
    a, b, qa, qb = _mk_two_tenants()
    ms = MultiSetBatchEngine([a[1], b[1]])
    pool = [BatchGroup(0, [qa, qb]), BatchGroup(1, [qa, qb])]
    trace = tmp_path / "t.jsonl"
    obs.enable(str(trace))
    out = ms.execute(pool, engine="megakernel", fallback=False)
    obs.disable()
    _assert_pooled_exact(out, (a, b), qa, qb)
    evs = _mega_events(trace)
    assert any(ev.get("vscan_steps", 0) >= 1
               and ev.get("vagg_steps", 0) >= 1 for ev in evs), \
        "pooled dispatch did not run analytics opcodes in-kernel"


def test_megakernel_analytics_parity_sharded(tmp_path):
    from roaringbitmap_tpu.parallel.sharded_engine import \
        ShardedBatchEngine

    a, b, qa, qb = _mk_two_tenants()
    sh = ShardedBatchEngine([a[1], b[1]])
    pool = [BatchGroup(0, [qa, qb]), BatchGroup(1, [qa, qb])]
    trace = tmp_path / "t.jsonl"
    obs.enable(str(trace))
    out = sh.execute(pool, engine="megakernel", fallback=False)
    obs.disable()
    _assert_pooled_exact(out, (a, b), qa, qb)
    evs = _mega_events(trace)
    assert any(ev.get("vscan_steps", 0) >= 1
               and ev.get("vagg_steps", 0) >= 1 for ev in evs), \
        "mesh dispatch did not run analytics opcodes in-kernel"


@pytest.mark.parametrize("fault_spec,", [
    "lowering@megakernel=1.0:0x16",              # land on pallas
    "lowering@megakernel=1.0,lowering@pallas=1.0:0x17",   # land on xla
])
def test_megakernel_analytics_fault_demotion_bit_exact(fault_spec):
    """A lowering fault in the v2 kernel walks the unchanged ladder
    and every landing answers the analytics pool bit-exactly."""
    bms, ds, col = build(71, 72)
    eng = BatchEngine(ds, result_cache=None)
    qs = _mega_queries()
    with faults.inject(fault_spec):
        got = eng.execute(qs, engine="megakernel")
    _assert_mega_exact(got, qs, bms, col, fault_spec)


def test_megakernel_two_phase_agreement():
    """The one-kernel lane agrees with the two-dispatch + readback
    baseline the OLAP bench measures against."""
    bms, ds, col = build(81, 82)
    eng = BatchEngine(ds, result_cache=None)
    qs = [q for q in _mega_queries() if expr.is_agg(q.expr)]
    assert len(qs) == 2                  # sum + top-k
    fused = eng.execute(qs, engine="megakernel", fallback=False)
    tp = two_phase_execute(eng, qs)
    for i, (f, t) in enumerate(zip(fused, tp)):
        assert (f.cardinality, f.value) == (t.cardinality, t.value), i
        if qs[i].form == "bitmap":
            assert f.bitmap == t.bitmap, i
