"""Closed program-signature lattice (ISSUE 13, docs/LATTICE.md).

What is pinned here:

- snap covering + idempotence over the vocabulary, and the env-knob
  profile round trip (``ROARING_TPU_WARMUP_PROFILE``);
- padded-vs-exact BIT-EXACT parity across (op x layout x engine rung)
  and on the 2x2 mesh — the lattice trades padding for a closed
  vocabulary, never results;
- plan-shape closure: different traffic mixes (ops present, operand
  rungs, tenant subsets) land on ONE compiled program per lattice
  point, and post-warmup steady state compiles NOTHING (the serving
  loop proves it under the fault clock);
- escape semantics: an out-of-vocabulary shape after seal is counted
  (``rb_lattice_escapes_total``), traced (``lattice.escape``), and
  still bit-exact;
- padding accounting: ``rb_lattice_padding_bytes`` moves and the
  per-dispatch padded fraction stays under the pinned bound;
- the serving predictor: a completed lattice warmup resets the
  service-time estimator, and the compile-majority ("chronic churn")
  window is capped so endless churn cannot inflate estimates forever.
"""

import json

import numpy as np
import pytest

from roaringbitmap_tpu import obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.obs import trace as obs_trace
from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                     BatchQuery,
                                                     random_query_pool)
from roaringbitmap_tpu.parallel.multiset import (BatchGroup,
                                                 MultiSetBatchEngine,
                                                 random_multiset_pool)
from roaringbitmap_tpu.parallel.sharded_engine import (ShardedBatchEngine,
                                                       default_mesh)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.runtime import lattice as rt_lattice
from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                       ServingRequest)
from roaringbitmap_tpu.utils import datasets

#: sparse rung lists: 2 points per engine family, every test shape
#: covered (8 residents, one key segment at this universe)
PROFILE = "q=16,;rows=16,;keys=2,;heads=both;pool=16,"

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda _s: None)


_misses = obs_metrics.compile_miss_total


def _escapes() -> int:
    return int(sum(
        inst.value
        for name, _labels, inst in obs_metrics.REGISTRY.instruments()
        if name == "rb_lattice_escapes_total"))


@pytest.fixture(autouse=True)
def _clean_lattice(monkeypatch):
    """Every test starts and ends lattice-free and fault-free: the
    lattice is process state, and the CI fault shard's env schedule
    would demote rungs mid-test and turn zero-compile pins flaky."""
    monkeypatch.delenv("ROARING_TPU_FAULTS", raising=False)
    monkeypatch.delenv(rt_lattice.ENV_PROFILE, raising=False)
    rt_lattice.deactivate()
    yield
    rt_lattice.deactivate()


@pytest.fixture(scope="module")
def bitmaps():
    return datasets.synthetic_bitmaps(8, seed=0x13, universe=1 << 17,
                                      density=0.01)


@pytest.fixture(scope="module")
def tenants():
    return [datasets.synthetic_bitmaps(8, seed=0x20 + i,
                                       universe=1 << 16, density=0.008)
            for i in range(4)]


# ------------------------------------------------------------ vocabulary

def test_snap_covering_and_idempotent():
    lat = rt_lattice.Lattice.from_profile(
        "q=8,64;rows=32;keys=4;pool=128,;heads=both;expr=2")
    p = lat.snap(ops=("or", "and"), q=9, rows=5, keys=3, heads=False)
    # covering: every dimension >= the need, drawn from the rung lists
    assert p.q == 64 and p.rows == 8 and p.keys == 4
    assert set(("or", "and")) <= set(p.ops)
    assert lat.contains(p)
    assert p in lat.enumerate_points()
    # idempotence: snapping a lattice point is the identity
    p2 = lat.snap(ops=p.ops, q=p.q, rows=p.rows, keys=p.keys,
                  heads=p.heads)
    assert p2 == p
    # beyond the maxima -> out of vocabulary, not a wrong covering
    assert lat.snap(ops=("or",), q=65, rows=1, keys=1,
                    heads=False) is None
    assert lat.snap(ops=("or",), q=1, rows=1, keys=5,
                    heads=False) is None


def test_profile_env_knob_round_trip(monkeypatch):
    spec = "q=8,64;rows=16,;keys=1,;pool=32,;heads=cardinality;expr=0"
    lat = rt_lattice.Lattice.from_profile(spec)
    # to_profile/from_profile is the identity on vocabularies
    assert rt_lattice.Lattice.from_profile(lat.to_profile()) == lat
    # the env knob activates the same lattice
    monkeypatch.setenv(rt_lattice.ENV_PROFILE, spec)
    got = rt_lattice.refresh_from_env()
    assert got == lat
    assert rt_lattice.active() == lat
    # bare ceiling expands to the pow2 ladder; explicit lists stay sparse
    assert rt_lattice.Lattice.from_profile("q=8").q == (1, 2, 4, 8)
    assert rt_lattice.Lattice.from_profile("q=8,").q == (8,)


def test_enumerate_is_finite_and_pool_dim_is_pooled_only():
    lat = rt_lattice.Lattice.from_profile(PROFILE)
    flat = lat.enumerate_points()
    pooled = lat.enumerate_points(pooled=True)
    assert len(flat) == 2          # one op set x 1q x 1r x 1k x 2 heads
    assert len(pooled) == 2        # x 1 pool rung
    assert all(p.pool == 0 for p in flat)
    assert all(p.pool == 16 for p in pooled)


# ----------------------------------------------------- bit-exact parity

@pytest.mark.parametrize("layout", ["dense", "counts"])
@pytest.mark.parametrize("engine", ["xla", "xla-vmap", "pallas"])
def test_padded_vs_exact_parity(bitmaps, layout, engine):
    """Snapped plans are BIT-EXACT vs the exact-shape plans for every
    op, both result forms, across layouts and engine rungs — padding
    is dead work by construction (identity rows, dead segments,
    owner-less dead buckets)."""
    pool = [BatchQuery(op, ops_, form=form)
            for op, ops_ in (("or", (0, 1, 2)), ("and", (1, 2, 3)),
                             ("xor", (0, 3)), ("andnot", (0, 1, 4)))
            for form in ("cardinality", "bitmap")]
    eng = BatchEngine.from_bitmaps(bitmaps, layout=layout)
    exact = eng.execute(pool, engine=engine, fallback=False)
    rt_lattice.activate(PROFILE)
    snapped = eng.execute(pool, engine=engine, fallback=False)
    plan = eng.plan(tuple(pool))
    assert plan.point is not None, "parity run must actually snap"
    for e, s, q in zip(exact, snapped, pool):
        assert e.cardinality == s.cardinality
        if q.form == "bitmap":
            assert e.bitmap == s.bitmap


def test_sharded_padded_parity(tenants):
    mesh = default_mesh(data=2)
    eng = ShardedBatchEngine.from_bitmap_sets(tenants, mesh=mesh)
    pool = random_multiset_pool([8] * 4, 10, seed=0x51)
    exact = eng.execute(pool, fallback=False)
    rt_lattice.activate(PROFILE)
    snapped = eng.execute(pool, fallback=False)
    assert [[r.cardinality for r in rows] for rows in exact] == \
        [[r.cardinality for r in rows] for rows in snapped]


# ------------------------------------------------------- shape closure

def test_plan_closure_one_program_for_diverse_flat_traffic(bitmaps):
    """Distinct op mixes and operand rungs all snap to one covering
    point -> ONE compiled program serves them all."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    rt_lattice.activate(PROFILE)
    mixes = [[BatchQuery("or", (0, 1))],
             [BatchQuery("and", (0, 1, 2, 3)), BatchQuery("xor", (1, 2))],
             [BatchQuery("andnot", (2, 0)), BatchQuery("or", (3, 4, 5)),
              BatchQuery("or", (0, 2, 4, 6))]]
    for pool in mixes:
        eng.execute(pool, engine="xla")
    assert len(eng._programs) == 1, \
        "diverse flat traffic must share one snapped program"
    points = {eng.plan(tuple(p)).point for p in mixes}
    assert len(points) == 1


def test_multiset_tenant_mix_closure(tenants):
    """Different referenced-tenant subsets are one program under the
    lattice: every pool references every set with a uniform padded row
    selection."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    rt_lattice.activate(PROFILE)
    pools = [[BatchGroup(0, [BatchQuery("or", (0, 1))]),
              BatchGroup(2, [BatchQuery("and", (1, 2))])],
             [BatchGroup(1, [BatchQuery("xor", (0, 3))]),
              BatchGroup(3, [BatchQuery("or", (2, 4))])],
             [BatchGroup(0, [BatchQuery("andnot", (0, 2))]),
              BatchGroup(1, [BatchQuery("or", (1, 5))]),
              BatchGroup(2, [BatchQuery("and", (0, 1, 2))])]]
    for pool in pools:
        flat = eng.execute(pool, engine="xla")
        # parity against the per-set sequential reference
        for g, rows in zip(pool, flat):
            for q, r in zip(g.queries, rows):
                ref = eng._engines[g.set_id]._sequential_one(q)
                assert r.cardinality == ref.cardinality
    assert len(eng._programs) == 1, \
        "tenant-mix diversity must not grow the pooled program cache"


def test_warmup_zero_compile_steady_state(bitmaps):
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    rep = eng.warmup(profile=PROFILE)
    assert rep["lattice"]["sealed"] is True
    lat = rt_lattice.active()
    assert lat is not None and lat.sealed
    m0, e0 = _misses(), _escapes()
    for seed in (1, 2, 3):
        pool = random_query_pool(8, 12, seed=seed, max_operands=5)
        got = eng.execute(pool)
        ref = eng._execute_sequential(pool)
        assert [r.cardinality for r in got] == \
            [r.cardinality for r in ref]
    assert _misses() == m0, "warmed lattice steady state compiled"
    assert _escapes() == e0 and lat.escapes == 0


# --------------------------------------------------------- escapes

def test_escape_counted_and_traced(bitmaps, tmp_path):
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    eng.warmup(profile=PROFILE)
    lat = rt_lattice.active()
    path = tmp_path / "lattice_trace.jsonl"
    obs_trace.enable(str(path))
    try:
        # 17 same-op queries > the q=16 rung: out of vocabulary
        big = [BatchQuery("or", (0, 1)) for _ in range(17)]
        got = eng.execute(big)
        ref = eng._execute_sequential(big)
        assert [r.cardinality for r in got] == \
            [r.cardinality for r in ref], "escapes must stay bit-exact"
    finally:
        obs_trace.disable()
    assert lat.escapes >= 1
    assert _escapes() >= 1
    events = [ev for line in path.read_text().splitlines()
              for ev in json.loads(line).get("events", [])
              if ev.get("name") == "lattice.escape"]
    assert events, "escape compile must emit a lattice.escape event"
    ev = events[0]
    assert ev["site"] == "batch_engine"
    assert ev["in_vocabulary"] is False
    assert isinstance(ev["compile_ms"], (int, float))


# --------------------------------------------------------- padding

def test_padding_fraction_bounded_and_metered(bitmaps):
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    eng.warmup(profile=PROFILE)
    pool = random_query_pool(8, 12, seed=9, max_operands=5)
    eng.execute(pool)
    mem = eng.last_dispatch_memory
    assert mem["lattice_padding_bytes"] > 0
    assert 0.0 <= mem["lattice_padding_fraction"] <= 0.97
    padded = int(sum(
        inst.value
        for name, labels, inst in obs_metrics.REGISTRY.instruments()
        if name == "rb_lattice_padding_bytes"
        and labels.get("site") == "batch_engine"))
    assert padded >= mem["lattice_padding_bytes"]


# --------------------------------------------------- serving loop

def _loop(engine, **kw) -> ServingLoop:
    kw.setdefault("pool_target", 8)
    kw.setdefault("default_deadline_ms", 600_000.0)
    kw.setdefault("max_queue", 4096)
    kw.setdefault("guard", NOSLEEP)
    return ServingLoop(engine, ServingPolicy(**kw))


def test_serving_zero_compile_steady_state_fault_clock(tenants):
    """The acceptance shape: a warmed-lattice loop replays a diverse
    stream on the fault clock and compiles NOTHING — p99 stops
    depending on traffic novelty because novelty stops existing."""
    faults.reset_clock()
    engine = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                  layout="dense")
    loop = _loop(engine)
    rep = loop.warmup(profile=PROFILE)
    assert rep["lattice"]["sealed"] and loop._lattice_warmed
    assert loop._s_per_q is None and not loop._walls
    rng = np.random.default_rng(0x77)
    ops = ("or", "and", "xor", "andnot")
    reqs = [ServingRequest(
        int(rng.integers(4)),
        BatchQuery(ops[int(rng.integers(4))],
                   tuple(int(x) for x in rng.choice(
                       8, size=int(rng.integers(2, 6)), replace=False))),
        tenant=f"t{int(rng.integers(4))}") for _ in range(96)]
    m0, e0 = _misses(), _escapes()
    tickets = loop.replay((i * 1e-3, r) for i, r in enumerate(reqs))
    assert all(t.ok for t in tickets)
    assert _misses() == m0, "warmed serving steady state compiled"
    assert _escapes() == e0
    snap = loop.snapshot()
    assert snap["lattice"] == {"sealed": True, "escapes": 0,
                               "warmed": True, "points": 2}
    for t in tickets[::13]:
        ref = engine._engines[t.request.set_id]._sequential_one(t.query)
        assert t.result.cardinality == ref.cardinality


def test_chronic_window_capped_and_warmup_resets(tenants):
    """The PR 10 predictor fix: chronic compile-majority windows stop
    calibrating the estimator after CHRONIC_CAP consecutive pools, and
    a completed lattice warmup resets the window outright."""
    faults.reset_clock()
    engine = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                  layout="dense")
    loop = _loop(engine)
    # churn: every pool is a novel program shape (op x operand-rung
    # matrix, rungs 2/4/8), so every dispatch compiles and the window
    # is compile-majority
    for i in range(loop.CHRONIC_CAP + 3):
        op = ("or", "and", "xor", "andnot")[i % 4]
        size = (2, 3, 5)[i // 4]
        t = loop.submit(ServingRequest(i % 4,
                                       BatchQuery(op, tuple(range(size)))))
        loop.pump(force=True)
        assert t.ok
    assert all(c for _, c in loop._walls)
    # capped: the run counter saturated past the cap, so the chronic
    # branch is off even though the window is still compile-majority
    assert loop._chronic_run > loop.CHRONIC_CAP
    # a completed lattice warmup resets the estimator state
    loop.warmup(profile=PROFILE)
    assert not loop._walls and loop._s_per_q is None
    assert loop._chronic_run == 0 and loop._lattice_warmed
    # post-warmup: compiled pools never calibrate the estimate — an
    # escape's wall is excluded as long as any warm sample exists
    t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1))))
    loop.pump(force=True)
    assert t.ok and not loop._walls[-1][1]   # in-lattice, no compile


def test_pool_rung_overflow_falls_back_exact(tenants):
    """A pool whose per-set row-selection need exceeds the pool rung
    vocabulary must fall back to EXACT shapes atomically — no dead
    buckets half-planted, no owner-less pseudo slots at readback (the
    review-found crash), results bit-exact."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    rt_lattice.activate("q=16,;rows=16,;keys=2,;heads=both;pool=2,")
    pool = [BatchGroup(0, [BatchQuery("or", (0, 1, 2, 3))]),
            BatchGroup(1, [BatchQuery("or", (0, 1))])]
    rows = eng.execute(pool, engine="xla")
    for g, rr in zip(pool, rows):
        for q, r in zip(g.queries, rr):
            ref = eng._engines[g.set_id]._sequential_one(q)
            assert r.cardinality == ref.cardinality
    plan = eng._plan_pool(eng._flatten(pool)[0])
    assert plan.point is None
    assert sum(len(b.qids) for b in plan.buckets) == 2, \
        "a refused snap must plant no dead pseudo slots"


def test_pool_rung_boundary_includes_padding_row(tenants):
    """Padded bucket cells always gather global row 0, so a pool whose
    raw need sits exactly on the rung must be judged WITH that row —
    either it snaps to a rung that covers the padded selection (staying
    in vocabulary) or it is refused atomically, never an off-vocabulary
    snapped shape."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    # operands (1,2,3,4): raw selection 4 rows + the padding row 0 = 5
    pool = [BatchGroup(0, [BatchQuery("or", (1, 2, 3, 4))]),
            BatchGroup(1, [BatchQuery("or", (1, 2))])]
    rt_lattice.activate("q=16,;rows=16,;keys=2,;heads=both;pool=4,")
    plan = eng._plan_pool(eng._flatten(pool)[0])
    assert plan.point is None, \
        "rung 4 cannot cover the 4-row-plus-padding-row selection"
    rt_lattice.activate("q=16,;rows=16,;keys=2,;heads=both;pool=8,")
    plan = eng._plan_pool(eng._flatten(pool)[0])
    assert plan.point is not None and plan.point.pool == 8
    assert all(sel.size == 8 for sel in plan.row_sel.values())
    rows = eng.execute(pool, engine="xla")
    for g, rr in zip(pool, rows):
        for q, r in zip(g.queries, rr):
            ref = eng._engines[g.set_id]._sequential_one(q)
            assert r.cardinality == ref.cardinality


# ------------------------------------------------- recommend_lattice

def test_recommend_lattice_covers_observed_trace(tenants, tmp_path):
    path = tmp_path / "observed.jsonl"
    obs_trace.enable(str(path))
    try:
        engine = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                      layout="dense")
        for seed in (1, 2):
            engine.execute(random_multiset_pool([8] * 4, 10, seed=seed),
                           engine="xla")
    finally:
        obs_trace.disable()
    rec = insights.recommend_lattice(str(path))
    assert rec["points"] >= 1 and rec["observed"]["q"]
    # the pooled-row dimension must be OBSERVED, not a fallback — the
    # trace above ran multi-tenant pools
    assert rec["observed"]["pool_rows"]
    lat = rt_lattice.Lattice.from_profile(rec["profile"])
    # the recommended vocabulary covers every observed shape
    assert lat.snap(ops=rt_lattice.OPS,
                    q=max(rec["observed"]["q"]),
                    rows=max(rec["observed"]["rows"]),
                    keys=max(rec["observed"]["keys"]),
                    heads=True) is not None
