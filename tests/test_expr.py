"""Expression-DAG query compiler acceptance (ISSUE 8).

Pins:
- fused expression execution bit-exact against host-side sequential
  evaluation across (DAG shape x layout x engine rung), including under
  injected oom/transient faults and on the sequential floor;
- canonicalization + CSE: associative flatten, idempotent dedupe, xor
  pairwise cancellation, the and(not) -> andnot rewrite, double-negation
  elimination, unbounded-complement rejection, and shared subtrees
  compiling to ONE reduce pseudo-query;
- the cardinality-only short circuit never materializes the result
  image (HBM-ledger-pinned, and the footprint model's output bytes
  shrink) and empty-pruned roots never touch the device;
- pooled expressions through MultiSetBatchEngine (S > 1) and a 2x2
  mesh ShardedBatchEngine, with the proactive HBM splitter splitting
  fused pools under ROARING_TPU_HBM_BUDGET (property test);
- warmup(rungs=("expr:2",)) pre-compiles the fused programs a matching
  execute then cache-hits;
- rb_expr_nodes_fused / rb_expr_launches_saved_total move, and
  explain() reports per-DAG-node predicted bytes/word-ops;
- CPU-proxy acceptance (slow lane): fused depth-2/3 expressions >= 2x
  the node-at-a-time evaluator's QPS, bit-exact.
"""

import json

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.parallel import (BatchEngine, BatchGroup, BatchQuery,
                                        DeviceBitmapSet,
                                        MultiSetBatchEngine)
from roaringbitmap_tpu.parallel import expr
from roaringbitmap_tpu.runtime import faults, guard


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture(scope="module")
def bitmaps():
    rng = np.random.default_rng(0xE54)
    out = []
    for i in range(8):
        vals = [rng.integers(0, 1 << 17, 2000).astype(np.uint32)]
        if i % 3 == 0:
            vals.append(np.arange(1 << 16, (1 << 16) + 6000,
                                  dtype=np.uint32))
        out.append(RoaringBitmap.from_values(
            np.unique(np.concatenate(vals))))
    return out


@pytest.fixture(scope="module")
def engine(bitmaps):
    return BatchEngine.from_bitmaps(bitmaps, layout="dense")


DEPTH2 = expr.and_(expr.or_(0, 1), expr.not_(2))          # (A|B) & ~C
DEPTH3 = expr.xor(expr.and_(expr.or_(0, 1), expr.or_(2, 3)),
                  expr.andnot(expr.or_(4, 5), 6))


def _want(e, bitmaps):
    return expr.evaluate_host(e, bitmaps)


# ------------------------------------------------------ canonicalize/CSE

def test_canonicalize_flatten_dedupe_sort():
    e = expr.canonicalize(expr.or_(expr.or_(2, 1), 1, expr.or_(0)))
    assert isinstance(e, expr.Node) and e.op == "or"
    assert tuple(c.index for c in e.children) == (0, 1, 2)
    # single-operand chains collapse to the leaf
    assert expr.canonicalize(expr.or_(3)) == expr.ref(3)
    # and dedupes too
    e = expr.canonicalize(expr.and_(1, 1, 0))
    assert tuple(c.index for c in e.children) == (0, 1)


def test_canonicalize_xor_cancellation():
    assert expr.canonicalize(expr.xor(expr.ref(1), expr.ref(1))) \
        is expr.EMPTY
    e = expr.canonicalize(expr.xor(1, 1, 2))
    assert e == expr.ref(2)


def test_canonicalize_not_rewrites():
    # and(x, not(y)) -> andnot(x, y)
    e = expr.canonicalize(DEPTH2)
    assert isinstance(e, expr.Node) and e.op == "andnot"
    # double negation
    assert expr.canonicalize(
        expr.and_(expr.ref(0), expr.not_(expr.not_(expr.ref(1))))
    ) == expr.canonicalize(expr.and_(0, 1))
    # nested andnot absorption: (h - s) - r == h - (s | r)
    e = expr.canonicalize(expr.andnot(expr.andnot(0, 1), 2))
    assert e.op == "andnot" and len(e.children) == 3
    # head in rests prunes to empty
    assert expr.canonicalize(expr.andnot(expr.ref(0), 1, 0)) \
        is expr.EMPTY


def test_unbounded_complement_rejected():
    with pytest.raises(ValueError):
        expr.canonicalize(expr.or_(0, expr.not_(1)))
    with pytest.raises(ValueError):
        expr.canonicalize(expr.not_(expr.ref(0)))
    with pytest.raises(ValueError):
        expr.canonicalize(expr.and_(expr.not_(0), expr.not_(1)))


def test_cse_shared_subtree_compiles_once(engine):
    sub = expr.or_(0, 1)
    e = expr.and_(sub, expr.xor(sub, expr.ref(2)))
    assert expr.dag_stats(e)["cse_saved"] > 0
    plan = engine.plan([expr.ExprQuery(e)])
    # the shared or(0,1) reduce registered exactly ONE pseudo-query
    pseudo = [pid for b in plan for pid in b.qids
              if plan.owner.get(pid) is None]
    assert len(pseudo) == 1
    [sec] = plan.fused
    assert sum(1 for st in sec.steps if st[0] == "reduce") == 1


# ----------------------------------------------------- engine parity

@pytest.mark.parametrize("layout,engines", [
    ("dense", ("xla", "xla-vmap", "pallas", "megakernel")),
    ("compact", ("xla", "pallas", "megakernel")),
    ("counts", ("xla", "megakernel")),
])
def test_fused_parity_vs_host_sequential(bitmaps, layout, engines):
    """(DAG shape x layout x engine rung) parity: fused expression pools
    bit-exact against the host sequential evaluator on every rung."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout=layout)
    pool = ([expr.ExprQuery(DEPTH2, form="bitmap"),
             expr.ExprQuery(DEPTH3, form="bitmap"),
             BatchQuery("xor", (1, 4), form="bitmap")]
            + expr.random_expr_pool(8, 5, depth=2, seed=7, form="bitmap"))
    want = [(_want(q.expr, bitmaps) if isinstance(q, expr.ExprQuery)
             else bitmaps[1] ^ bitmaps[4]) for q in pool]
    for e in engines:
        got = eng.execute(pool, engine=e, fallback=False)
        for i, (g, w) in enumerate(zip(got, want)):
            assert g.cardinality == w.cardinality, (layout, e, i)
            assert g.bitmap == w, (layout, e, i)


def test_flat_root_is_a_batch_query(engine, bitmaps):
    """A single-node expression IS a flat query: identical results, no
    fused section, same bucket machinery."""
    q_expr = expr.ExprQuery(expr.or_(1, 2, 3), form="bitmap")
    q_flat = BatchQuery("or", (1, 2, 3), form="bitmap")
    plan = engine.plan([q_expr])
    assert not plan.fused and plan.exprs[0].kind == "flat"
    [a] = engine.execute([q_expr])
    [b] = engine.execute([q_flat])
    assert a.cardinality == b.cardinality and a.bitmap == b.bitmap


def test_adhoc_bitmap_leaf(engine, bitmaps):
    rng = np.random.default_rng(11)
    ad = RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 3000).astype(np.uint32)))
    e = expr.and_(expr.or_(0, 1), expr.bitmap(ad))
    [got] = engine.execute([expr.ExprQuery(e, form="bitmap")])
    want = (bitmaps[0] | bitmaps[1]) & ad
    assert got.bitmap == want
    # adhoc root short-circuits on the host
    [r] = engine.execute([expr.ExprQuery(expr.bitmap(ad))])
    assert r.cardinality == ad.cardinality


def test_fused_parity_under_faults(engine, bitmaps):
    pool = [expr.ExprQuery(DEPTH2, form="bitmap"),
            expr.ExprQuery(DEPTH3, form="bitmap")]
    want = [_want(q.expr, bitmaps) for q in pool]
    with faults.inject("oom=0.4,transient=0.1:0xE1"):
        got = engine.execute(pool, engine="xla")
    assert [g.bitmap for g in got] == want
    with faults.inject("lowering=1.0:0xE2"):    # every device rung dead
        got = engine.execute(pool, engine="xla")
    assert [g.bitmap for g in got] == want


# ------------------------------------------------------ short circuits

def test_cardinality_only_never_materializes(engine, bitmaps):
    """Ledger pin: a cardinality-only expression registers no resident
    bytes, returns no bitmap, and the footprint model's output bytes
    shrink by the root image vs the bitmap form."""
    q = expr.ExprQuery(DEPTH2)          # form="cardinality"
    ledger_before = obs_memory.LEDGER.snapshot()
    [got] = engine.execute([q])
    assert obs_memory.LEDGER.snapshot() == ledger_before
    assert got.bitmap is None
    assert got.cardinality == _want(DEPTH2, bitmaps).cardinality
    card_sig = engine.plan([q]).expr_signature
    bm_sig = engine.plan(
        [expr.ExprQuery(DEPTH2, form="bitmap")]).expr_signature
    card_b = insights.predict_expr_dispatch_bytes(card_sig, "xla")
    bm_b = insights.predict_expr_dispatch_bytes(bm_sig, "xla")
    k_root = card_sig[0][-1]
    assert bm_b["output_bytes"] - card_b["output_bytes"] \
        == k_root * insights.ROW_BYTES


def test_empty_pruning_skips_the_device(engine):
    """xor(x, x) and disjoint-AND roots prune at plan time: correct
    empty results with zero compiled programs."""
    lo = RoaringBitmap.from_values(np.arange(100, dtype=np.uint32))
    hi = RoaringBitmap.from_values(
        np.arange(1 << 20, (1 << 20) + 100, dtype=np.uint32))
    eng = BatchEngine.from_bitmaps([lo, hi], layout="dense")
    n_programs = len(eng._programs)
    got = eng.execute([
        expr.ExprQuery(expr.xor(expr.ref(0), expr.ref(0)), form="bitmap"),
        expr.ExprQuery(expr.and_(0, 1), form="bitmap"),
    ])
    assert [r.cardinality for r in got] == [0, 0]
    assert got[0].bitmap == RoaringBitmap()
    assert len(eng._programs) == n_programs   # nothing compiled


# ------------------------------------------------- explain + budget

def test_explain_reports_per_dag_node_costs(engine):
    rep = engine.explain([expr.ExprQuery(DEPTH3, form="bitmap"),
                          BatchQuery("or", (0, 1))])
    [erow] = rep["exprs"]
    assert erow["nodes"] >= 3 and erow["combine_nodes"] >= 1
    assert erow["predicted_bytes"] > 0 and erow["est_word_ops"] > 0
    kinds = {r["kind"] for r in erow["per_node"]}
    assert "combine" in kinds
    assert all(r["est_bytes"] >= 0 and r["est_word_ops"] >= 0
               for r in erow["per_node"])
    assert rep["predicted"]["expr_bytes"] > 0
    assert rep["queries"][0]["op"] == "expr"
    assert rep["queries"][1]["op"] == "or"


def test_budget_splits_fused_batches(bitmaps, tmp_path):
    """Property: under ROARING_TPU_HBM_BUDGET the proactive splitter
    halves fused expression batches BEFORE dispatch, every dispatched
    launch's prediction fits the budget, bit-exact."""
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    pool = expr.random_expr_pool(8, 12, depth=2, seed=23, form="bitmap")
    want = [_want(q.expr, bitmaps) for q in pool]
    full = eng.predict_dispatch_bytes(pool)
    budget = max(1, full // 3)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    got = eng.execute(pool, engine="xla",
                      policy=guard.GuardPolicy(hbm_budget=budget))
    obs.disable()
    assert [g.bitmap for g in got] == want
    assert eng.proactive_split_count > 0
    spans = [json.loads(line) for line in open(path)]
    mems = [ev for s in spans if s["name"] == "batch.dispatch"
            for ev in s["events"] if ev["name"] == "batch.memory"]
    assert mems and all(ev["predicted_bytes"] <= budget for ev in mems)


# ---------------------------------------------------- pooled engines

@pytest.fixture(scope="module")
def tenants():
    rng = np.random.default_rng(0xE55)
    return [[RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(6)] for _ in range(3)]


def _expr_pool(form="bitmap"):
    return [BatchGroup(sid, [
        expr.ExprQuery(DEPTH2, form=form),
        BatchQuery("xor", (1, 3), form=form),
        expr.ExprQuery(expr.xor(expr.or_(2, 3), expr.and_(4, 5)),
                       form=form)]) for sid in range(3)]


def _assert_pool_parity(got, tenants, tag):
    for sid, rows in enumerate(got):
        srcs = tenants[sid]
        assert rows[0].bitmap == _want(DEPTH2, srcs), (tag, sid, 0)
        assert rows[1].bitmap == (srcs[1] ^ srcs[3]), (tag, sid, 1)
        assert rows[2].bitmap == _want(
            expr.xor(expr.or_(2, 3), expr.and_(4, 5)), srcs), (tag, sid, 2)


def test_multiset_pooled_expressions(tenants):
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    pool = _expr_pool()
    for e in ("xla", "xla-vmap", "pallas", "megakernel"):
        _assert_pool_parity(eng.execute(pool, engine=e), tenants, e)
    with faults.inject("lowering=1.0:0xE3"):
        _assert_pool_parity(eng.execute(pool, engine="xla"), tenants,
                            "floor")


def test_multiset_budget_splits_fused_pools(tenants, tmp_path):
    """The acceptance property one level up: the pooled proactive HBM
    splitter splits fused expression POOLS under the budget, bit-exact,
    every dispatched launch within budget."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    pool = _expr_pool()
    full = eng.predict_dispatch_bytes(pool)
    budget = max(1, full // 3)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    got = eng.execute(pool, engine="xla",
                      policy=guard.GuardPolicy(hbm_budget=budget))
    obs.disable()
    _assert_pool_parity(got, tenants, "budget")
    assert eng.proactive_split_count > 0
    spans = [json.loads(line) for line in open(path)]
    mems = [ev for s in spans if s["name"] == "multiset.dispatch"
            for ev in s["events"] if ev["name"] == "multiset.memory"]
    assert mems and all(ev["predicted_bytes"] <= budget for ev in mems)


def test_sharded_mesh_expressions(tenants):
    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu.parallel import ShardedBatchEngine

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("rows", "data"))
    ms = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    sh = ShardedBatchEngine(ms._engines, mesh=mesh)
    pool = _expr_pool()
    _assert_pool_parity(sh.execute(pool), tenants, "mesh")
    # mesh -> single demotion stays bit-exact for fused pools
    with faults.inject("lowering@mesh=1.0:0xE4"):
        _assert_pool_parity(sh.execute(pool), tenants, "demoted")


# ------------------------------------------------- warmup + metrics

def test_warmup_expr_rungs_precompile(bitmaps):
    eng = BatchEngine.from_bitmaps(bitmaps, layout="dense")
    rep = eng.warmup(rungs=("expr:2",))
    assert rep["programs"]
    hits0 = eng._programs.stats()["hits"]
    n0 = len(eng._programs)
    got = eng.execute(expr.rung_expressions(2, eng.n), engine="auto")
    assert len(got) == len(expr.rung_expressions(2, eng.n))
    assert len(eng._programs) == n0          # nothing new compiled
    assert eng._programs.stats()["hits"] > hits0


def test_fused_metrics_move(engine, bitmaps):
    obs.reset()
    pool = [expr.ExprQuery(DEPTH2), expr.ExprQuery(DEPTH3)]
    engine.execute(pool, engine="xla")
    snap = obs.snapshot()
    fused = snap["counters"]["rb_expr_nodes_fused"][0]["value"]
    saved = snap["counters"]["rb_expr_launches_saved_total"][0]["value"]
    assert fused >= 4            # both DAGs' op nodes rode one launch
    assert saved > 0


def test_device_bitmapset_evaluate(bitmaps):
    ds = DeviceBitmapSet(bitmaps, layout="dense")
    want = _want(DEPTH2, bitmaps)
    assert ds.evaluate(DEPTH2) == want.cardinality
    assert ds.evaluate(DEPTH2, form="bitmap") == want


def test_deep_shared_dag_planning_is_polynomial():
    """A deeply CSE-shared dag has exponential TREE size by
    construction; canonicalize/dag_stats/compile must stay O(dag)
    (per-node hash/sort-key caching + interning), not walk the tree —
    the regression that once made a depth-24 shared expression hang the
    planner."""
    import time

    a, b = expr.ref(0), expr.ref(1)
    for i in range(40):
        a, b = expr.or_(a, expr.and_(b, expr.ref(2 + i % 3))), \
            expr.xor(a, b)
    t0 = time.perf_counter()
    stats = expr.dag_stats(expr.xor(a, b))
    wall = time.perf_counter() - t0
    assert stats["cse_saved"] > 0 and stats["tree_nodes"] > stats["nodes"]
    assert wall < 5.0, f"shared-dag stats took {wall:.1f}s"


def test_node_at_a_time_bare_leaf_root_never_aliases(engine, bitmaps):
    """The unfused evaluator must clone bare-leaf roots: mutating its
    result must not corrupt the engine's host-source (shadow-reference)
    cache."""
    [r] = expr.execute_node_at_a_time(
        engine, [expr.ExprQuery(expr.ref(0), form="bitmap")])
    before = engine._host_sources()[0].cardinality
    r.bitmap.ior(RoaringBitmap.from_values(
        np.array([1, 2, 3], np.uint32)))
    assert engine._host_sources()[0].cardinality == before


def test_adhoc_snapshot_survives_mutation(engine, bitmaps):
    """AdHoc leaves snapshot at construction: mutating the source after
    building the query must not change a cached plan's answer (nor the
    host reference it is checked against)."""
    ad = RoaringBitmap.from_values(np.array([1, 70000], np.uint32))
    q = expr.ExprQuery(expr.and_(expr.or_(0, 1), expr.bitmap(ad)),
                       form="bitmap")
    [r1] = engine.execute([q])
    ad.add(5)
    [r2] = engine.execute([q])
    assert r1.bitmap == r2.bitmap == expr.evaluate_host(q.expr, bitmaps)


def test_node_at_a_time_reference_parity(engine, bitmaps):
    pool = [expr.ExprQuery(DEPTH2, form="bitmap"),
            expr.ExprQuery(DEPTH3, form="bitmap")]
    fused = engine.execute(pool)
    unfused = expr.execute_node_at_a_time(engine, pool)
    for f, u in zip(fused, unfused):
        assert f.cardinality == u.cardinality and f.bitmap == u.bitmap


# ---------------------------------------------------- CPU-proxy perf

def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


@pytest.mark.slow
def test_fused_2x_vs_node_at_a_time():
    """Acceptance: fused depth-2/3 expressions >= 2x the node-at-a-time
    QPS on the CPU proxy (one launch vs one launch per reduce node),
    bit-exact."""
    rng = np.random.default_rng(0xE56)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 16, 400).astype(np.uint32))
        for _ in range(8)]
    eng = BatchEngine.from_bitmaps(bms, layout="dense")
    pool = (expr.random_expr_pool(8, 8, depth=2, seed=31)
            + expr.random_expr_pool(8, 8, depth=3, seed=32))
    fused = eng.execute(pool, engine="xla")
    unfused = expr.execute_node_at_a_time(eng, pool)
    assert [f.cardinality for f in fused] == \
        [u.cardinality for u in unfused]
    t_fused = min(_timed(lambda: eng.execute(pool, engine="xla"))
                  for _ in range(5))
    t_node = min(_timed(lambda: expr.execute_node_at_a_time(eng, pool))
                 for _ in range(5))
    assert t_node >= 2.0 * t_fused, (t_node, t_fused, t_node / t_fused)
