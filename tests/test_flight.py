"""Observability plane acceptance (flight recorder + statusz + trace
propagation — docs/OBSERVABILITY.md).

Pins:
- cross-host trace propagation: ``inject``/``extract`` round-trip, a
  local contextvar parent always wins over a remote context, and a
  forwarded-then-rerouted request on a 2-host simulated pod stitches
  into ONE trace id covering pod.route / serving.admit / pod.reroute /
  serving.request (the tentpole acceptance assertion);
- the black-box flight recorder: bounded ring, span-close feed (only
  while tracing is enabled), schema-valid atomic dumps on trigger,
  per-reason debounce, dumps fired by a real SLO miss and by a
  ``crash@torn`` injected fault;
- trace JSONL rotation under ``ROARING_TPU_TRACE_MAX_BYTES`` with the
  keep-last-N shift and ``rb_trace_rotations_total``;
- statusz: the monotone/idempotent counter merge, and a 2-host
  simulated pod reporting BOTH hosts' state in one merged report via
  ``obs.statusz()`` / ``fd.statusz()``;
- the disabled-tracer fast path stays a shared no-op while the flight
  ring is armed (the tools/check_obs_overhead.py contract).
"""

import json
import os

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.obs import flight as obs_flight
from roaringbitmap_tpu.obs import statusz as obs_statusz
from roaringbitmap_tpu.obs import trace as obs_trace
from roaringbitmap_tpu.parallel import BatchQuery, DeviceBitmapSet, podmesh
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (PodFrontDoor, ServingLoop,
                                       ServingPolicy, ServingRequest)

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)
EASY_MS = 300_000.0


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    faults.reset_clock()
    obs_flight.configure(dir=str(tmp_path / "flight"))
    obs_flight.reset()
    yield
    obs.disable()
    obs.reset()
    obs_flight.configure(dir=None)
    obs_flight.reset()
    faults.reset_clock()


@pytest.fixture(scope="module")
def tenant_sets():
    rng = np.random.default_rng(0xF117)
    return [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 15, 600).astype(np.uint32)))
        for _ in range(4)], layout="dense") for _ in range(3)]


def _policy(**kw) -> ServingPolicy:
    kw.setdefault("guard", NOSLEEP)
    kw.setdefault("default_deadline_ms", EASY_MS)
    return ServingPolicy(**kw)


def _pod_front_door(tenant_sets) -> PodFrontDoor:
    return PodFrontDoor(
        tenant_sets, pod=podmesh.PodMesh.simulate(2),
        plan=podmesh.PlacementPlan(
            regimes=("replicated-2", "local", "local"),
            hosts=((0, 1), (0,), (1,)), bytes_per_host=(0, 0)),
        policy=_policy(pool_target=4))


def _dumps(tmp_path) -> list:
    fdir = tmp_path / "flight"
    if not fdir.is_dir():
        return []
    return [json.loads((fdir / f).read_text())
            for f in sorted(os.listdir(fdir)) if f.startswith("flight-")]


# ------------------------------------------------------ trace propagation


def test_inject_extract_roundtrip(tmp_path):
    obs.enable(str(tmp_path / "t.jsonl"))
    with obs.span("outer", site="test") as sp:
        ctx = obs_trace.inject()
        assert ctx == {"trace_id": sp.trace_id, "span_id": sp.span_id}
        assert obs_trace.extract(ctx) == (sp.trace_id, sp.span_id)
    assert obs_trace.inject() is None          # outside any span
    assert obs_trace.extract(None) is None
    assert obs_trace.extract({"trace_id": "x"}) is None   # malformed


def test_span_from_parents_into_remote_context(tmp_path):
    path = str(tmp_path / "t.jsonl")
    obs.enable(path)
    with obs.span("origin") as sp:
        ctx = obs_trace.inject()
    with obs_trace.span_from(ctx, "continued", site="test"):
        pass
    with obs.span("local_parent"):
        # a live contextvar parent WINS over the remote context: the
        # remote ctx must never re-root spans already inside a tree
        with obs_trace.span_from(ctx, "nested_local") as inner:
            assert inner.trace_id != sp.trace_id
    obs.disable()
    spans = {s["name"]: s for s in map(json.loads, open(path))}
    assert spans["continued"]["trace_id"] == sp.trace_id
    assert spans["continued"]["parent_id"] == sp.span_id
    assert spans["nested_local"]["parent_id"] \
        == spans["local_parent"]["span_id"]


def test_span_from_none_context_roots(tmp_path):
    path = str(tmp_path / "t.jsonl")
    obs.enable(path)
    with obs_trace.span_from(None, "rootish") as sp:
        assert sp.parent_id is None and sp.trace_id == sp.span_id
    obs.disable()


def test_forwarded_then_rerouted_request_stitches_one_trace(
        tenant_sets, tmp_path):
    """The tentpole acceptance pin: one trace id covers admission on
    the entry host, the forwarding hop, the reroute after host loss,
    and the final per-request outcome span."""
    path = str(tmp_path / "t.jsonl")
    obs.enable(path)
    fd = _pod_front_door(tenant_sets)
    tickets = [fd.submit(ServingRequest(
        i % 3, BatchQuery("or", (0, 1, 2)), tenant=f"t{i % 3}"),
        via_host=1 - (i % 2)) for i in range(8)]
    victim = next(h for h in (0, 1)
                  if any(t.pod_host == h for t in tickets))
    fd.fail_host(victim)
    fd.drain()
    obs.disable()
    assert all(t.status == "done" for t in tickets)
    by_trace: dict = {}
    for s in map(json.loads, open(path)):
        by_trace.setdefault(s["trace_id"], set()).add(s["name"])
    need = {"pod.route", "serving.admit", "pod.reroute",
            "serving.request"}
    stitched = [tid for tid, names in by_trace.items() if need <= names]
    assert stitched, {tid: sorted(n & need)
                      for tid, n in by_trace.items() if n & need}


def test_host_loss_under_injected_fault_stitches_and_dumps(
        tenant_sets, tmp_path):
    """Same pin driven through the fault machinery (``coordinator@``)
    instead of an explicit fail_host call: the host loss dumps a
    flight artifact and the rerouted tickets keep their trace."""
    path = str(tmp_path / "t.jsonl")
    obs.enable(path)
    fd = _pod_front_door(tenant_sets)
    tickets = [fd.submit(ServingRequest(
        i % 3, BatchQuery("or", (0, 1, 2)), tenant=f"t{i % 3}"),
        via_host=1 - (i % 2)) for i in range(8)]
    victim = next(h for h in (0, 1)
                  if any(t.pod_host == h for t in tickets))
    with faults.inject(f"coordinator@host{victim}=1.0:13"):
        fd.pump()
        fd.drain()
    obs.disable()
    assert fd.stats["reroutes"] > 0
    assert all(t.status == "done" for t in tickets)
    assert any(d["trigger"] == "host_lost" for d in _dumps(tmp_path))


def test_maintenance_job_parents_into_submitter_trace(tmp_path):
    from roaringbitmap_tpu.mutation.maintenance import MaintenanceWorker

    path = str(tmp_path / "t.jsonl")
    obs.enable(path)
    w = MaintenanceWorker(start=False)
    with obs.span("mutation.apply_delta", site="test") as sp:
        w.submit(lambda: None, kind="repack", desc="t")
    w.drain()
    obs.disable()
    spans = {s["name"]: s for s in map(json.loads, open(path))}
    job = spans["mutation.maintenance"]
    assert job["trace_id"] == sp.trace_id
    assert job["parent_id"] == sp.span_id
    assert job["tags"]["ok"] is True


# ---------------------------------------------------------- trace rotation


def test_trace_rotation_keeps_last_n(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    obs.reset()
    obs_trace.enable(path, max_bytes=2000, keep=2)
    for i in range(200):
        with obs.span("rotate_me", i=i, pad="x" * 40):
            pass
    obs.disable()
    assert os.path.exists(path) and os.path.exists(path + ".1")
    rot = obs.snapshot()["counters"].get("rb_trace_rotations_total", [])
    assert sum(r["value"] for r in rot) >= 1
    # every surviving segment is schema-valid JSONL
    for p in (path, path + ".1"):
        for line in open(p):
            rec = json.loads(line)
            assert rec["name"] == "rotate_me" and "span_id" in rec


def test_trace_rotation_env_knobs(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("ROARING_TPU_TRACE", path)
    monkeypatch.setenv("ROARING_TPU_TRACE_MAX_BYTES", "1500")
    monkeypatch.setenv("ROARING_TPU_TRACE_KEEP", "3")
    obs.refresh_from_env()
    assert obs.enabled()
    for i in range(200):
        with obs.span("rotate_env", i=i, pad="y" * 40):
            pass
    obs.disable()
    assert os.path.exists(path + ".1")


# --------------------------------------------------------- flight recorder


def test_ring_is_bounded():
    obs_flight.configure(capacity=8)
    try:
        for i in range(40):
            obs_flight.record("error", i=i)
        snap = obs_flight.snapshot()
        assert snap["capacity"] == 8 and snap["occupancy"] == 8
    finally:
        obs_flight.configure(capacity=obs_flight.DEFAULT_CAPACITY)


def test_span_closes_feed_ring_only_while_tracing(tmp_path):
    with obs.span("invisible", site="test"):
        pass                       # tracer off: no span summary
    assert not any(e.get("kind") == "span"
                   for e in list(obs_flight._ring))
    obs.enable(str(tmp_path / "t.jsonl"))
    with obs.span("visible", site="test", error_class="Boom"):
        pass
    obs.disable()
    summaries = [e for e in list(obs_flight._ring)
                 if e.get("kind") == "span"]
    assert any(e["name"] == "visible" and e.get("site") == "test"
               and e.get("error_class") == "Boom" for e in summaries)


def test_trigger_dumps_schema_valid_and_atomic(tmp_path):
    obs_flight.record("error", site="test", error_class="ValueError")
    p = obs_flight.trigger("unit_test", site="test", detail=7)
    assert p is not None and os.path.exists(p)
    assert not any(f.endswith(".tmp")
                   for f in os.listdir(tmp_path / "flight"))
    doc = json.loads(open(p).read())
    assert doc["kind"] == "rb_flight" and doc["version"] >= 1
    assert doc["trigger"] == "unit_test"
    assert doc["context"] == {"site": "test", "detail": 7}
    kinds = [e["kind"] for e in doc["events"]]
    assert "error" in kinds and "trigger" in kinds
    assert isinstance(doc["metrics_delta"], dict)
    # the dump doubles as a check_trace-accepted artifact
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_trace", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_trace.py"))
    ct = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ct)
    assert ct.validate(p) == []


def test_trigger_debounce_per_reason(monkeypatch):
    monkeypatch.setenv("ROARING_TPU_FLIGHT_DEBOUNCE_S", "3600")
    assert obs_flight.trigger("same_reason") is not None
    assert obs_flight.trigger("same_reason") is None    # suppressed
    assert obs_flight.trigger("other_reason") is not None
    sup = obs.snapshot()["counters"].get("rb_flight_suppressed_total", [])
    assert any(r["labels"].get("reason") == "same_reason"
               and r["value"] >= 1 for r in sup)


def test_slo_miss_dumps_flight(tenant_sets, tmp_path):
    """A real missed deadline on the serving loop fires the slo_miss
    trigger with the tenant/set context."""
    from roaringbitmap_tpu.parallel import MultiSetBatchEngine

    eng = MultiSetBatchEngine(tenant_sets)
    loop = ServingLoop(eng, _policy(pool_target=4, shed=False))
    t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                   tenant="late", deadline_ms=10.0))
    faults.advance_clock(0.5)
    loop.pump(force=True)
    assert t.status == "done" and t.missed is True
    dumps = _dumps(tmp_path)
    miss = [d for d in dumps if d["trigger"] == "slo_miss"]
    assert miss, [d["trigger"] for d in dumps]
    assert miss[0]["context"]["tenant"] == "late"


def test_crash_torn_dumps_flight(tmp_path):
    from roaringbitmap_tpu.mutation import durability

    rng = np.random.default_rng(0xC4A5)
    dt = durability.DurableTenant(
        DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 14, 300).astype(np.uint32)))
            for _ in range(3)]),
        root=str(tmp_path / "dur"), tenant="fl",
        policy=durability.FlushPolicy(mode="never"),
        snapshot_every=None)
    dt.apply_delta(adds={0: [4242]})
    with faults.inject("crash@torn=1.0:3"):
        with pytest.raises(errors.InjectedCrash):
            dt.apply_delta(adds={1: [4243]})
    dumps = [d for d in _dumps(tmp_path) if d["trigger"] == "crash"]
    assert dumps, "crash@torn left no flight dump"
    assert dumps[0]["context"]["mode"] == "torn"
    assert dumps[0]["context"]["point"] in (
        "pre_append", "pre_apply", "post_apply")
    # the crash also landed in the ring as a typed error event
    assert any(e["kind"] == "error" for e in dumps[0]["events"])


def test_disabled_tracer_stays_noop_with_ring_armed():
    obs_flight.record("error", site="test")
    assert obs.span("probe", q=1) is obs.trace._NOOP
    assert obs.trace._on_close is not None


# ----------------------------------------------------------------- statusz


def test_merge_counters_is_monotone_and_idempotent():
    a = {"rb_x_total": [{"labels": {"site": "a"}, "value": 3}],
         "rb_y_total": [{"labels": {}, "value": 10}]}
    b = {"rb_x_total": [{"labels": {"site": "a"}, "value": 5}],
         "rb_z_total": [{"labels": {}, "value": 1}]}
    merged = obs_statusz.merge_counters([a, b])
    assert merged["rb_x_total"][0]["value"] == 5          # max, not sum
    assert merged["rb_y_total"][0]["value"] == 10
    assert merged["rb_z_total"][0]["value"] == 1
    # commutative + idempotent: order and re-delivery change nothing
    assert obs_statusz.merge_counters([b, a, b]) == merged
    assert obs_statusz.merge_counters([merged, a, b]) == merged


def test_merge_same_host_newest_wins():
    d1 = {"kind": "rb_statusz", "version": 1, "merged": False,
          "host": "0", "pid": 1, "t": 1.0, "obs": {"counters": {}},
          "flight": {}, "sections": {"serving": {"level": 0}}}
    d2 = dict(d1, t=2.0, sections={"serving": {"level": 2}})
    m = obs_statusz.merge([d1, d2])
    assert m["hosts"]["0"]["sections"]["serving"]["level"] == 2
    # merging the merged doc with its inputs is idempotent
    m2 = obs_statusz.merge([m, d1, d2])
    assert m2["hosts"]["0"] == m["hosts"]["0"]
    assert m2["counters"] == m["counters"]


def test_two_host_pod_statusz_reports_both_hosts(tenant_sets):
    fd = _pod_front_door(tenant_sets)
    tickets = [fd.submit(ServingRequest(
        i % 3, BatchQuery("or", (0, 1)), tenant=f"t{i % 3}"))
        for i in range(4)]
    fd.drain()
    assert all(t.status == "done" for t in tickets)
    sz = fd.statusz()
    assert sz["kind"] == "rb_statusz" and sz["merged"] is True
    assert {"0", "1"} <= set(sz["hosts"])
    for h in ("0", "1"):
        serving = sz["hosts"][h]["sections"]["serving"]
        assert "level" in serving and "backlog" in serving
    assert "placement" in sz and "stats" in sz
    # the provider registration makes the package-level entry point see
    # the same hosts without a front-door handle
    top = obs.statusz()
    assert {"0", "1"} <= set(top["hosts"])
    # and the markdown renderer accepts both shapes
    page = obs.render_markdown(sz)
    assert "## host 0" in page and "## host 1" in page
    assert obs.render_markdown(sz["hosts"]["0"]).startswith("#")


def test_statusz_carries_journal_and_flight_sections(tmp_path):
    from roaringbitmap_tpu.mutation import durability

    rng = np.random.default_rng(0x57A7)
    dt = durability.DurableTenant(
        DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 14, 300).astype(np.uint32)))
            for _ in range(3)]),
        root=str(tmp_path / "dur"), tenant="sz",
        policy=durability.FlushPolicy(mode="never"),
        snapshot_every=None)
    dt.apply_delta(adds={0: [77]})
    obs_flight.trigger("statusz_test")
    doc = obs_statusz.local_doc(host="h0")
    tenants = {t["tenant"]: t for t in doc["journal"]}
    assert "sz" in tenants
    assert tenants["sz"]["unflushed_bytes"] > 0      # mode="never"
    assert tenants["sz"]["snapshot_age_s"] >= 0.0
    assert any(r["reason"] == "statusz_test"
               for r in doc["flight"]["recent_triggers"])
    dt.close()
