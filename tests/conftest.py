"""Test harness config: force an 8-device virtual CPU mesh.

The "fake cluster" strategy from SURVEY.md §4: multi-device code paths are
exercised on the CPU backend with xla_force_host_platform_device_count=8,
mirroring the reference's determinism tests under varied ForkJoinPool sizes
(ParallelAggregationTest.java:26-40).  Must run before any jax import; the
axon TPU plugin registered by sitecustomize is overridden via jax.config.

On-TPU lane (VERDICT r2 item 5): RB_TPU_TESTS=1 skips the CPU pinning so
tests/test_on_tpu.py runs against the real backend with compiled Mosaic
kernels.  One command:

    RB_TPU_TESTS=1 python -m pytest tests/test_on_tpu.py -q

(Only that file — the rest of the suite expects the 8-device CPU mesh.)
"""

import os

RB_TPU_TESTS = os.environ.get("RB_TPU_TESTS") == "1"

if not RB_TPU_TESTS:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import jax  # noqa: E402

if not RB_TPU_TESTS:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0xC0FFEE)


@pytest.fixture(scope="session", autouse=True)
def _devices():
    if RB_TPU_TESTS:
        return  # real backend; test_on_tpu guards on jax.default_backend()
    assert jax.default_backend() == "cpu"
    assert len(jax.devices()) == 8, "tests expect 8 virtual CPU devices"
