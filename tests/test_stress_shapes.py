"""Parity tests at the jmh stress-shape extremes.

The synthetic key-layout extremes of jmh/src/jmh/java/org/roaringbitmap/
aggregation/{and,andnot,or,xor}/{bestcase,worstcase,identical} (pairwise)
and the wide analogs the verdict called for: segment skew is the blocked
layout's failure mode — all-size-1 segments maximize block padding, one
giant segment maximizes sequential depth — and nothing else in the suite
pins the engines' bit-exactness there.  Small scale (the benchmark tier,
benchmarks/stress.py, runs the big shapes); both engines every time.
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "benchmarks"))

from stress import make_pair, make_wide  # noqa: E402

from roaringbitmap_tpu.parallel import aggregation, fast_aggregation

N, KEYS = 20, 24

WIDE_SHAPES = ["disjoint", "shared", "giant", "identical"]


@pytest.fixture(scope="module", params=WIDE_SHAPES)
def wide_case(request):
    shape = request.param
    bms = make_wide(shape, "sparse", N, KEYS, seed=7)
    oracle = {}
    for op, fn in (("or", fast_aggregation.or_),
                   ("xor", fast_aggregation.xor),
                   ("and", fast_aggregation.and_)):
        oracle[op] = fn(*bms)
    return shape, bms, oracle


@pytest.mark.parametrize("engine", ["xla", "pallas"])
@pytest.mark.parametrize("op", ["or", "xor"])
def test_wide_engine_parity(wide_case, op, engine):
    shape, bms, oracle = wide_case
    fn = {"or": aggregation.or_, "xor": aggregation.xor}[op]
    assert fn(*bms, engine=engine) == oracle[op], (shape, op, engine)


def test_wide_and_parity(wide_case):
    shape, bms, oracle = wide_case
    assert aggregation.and_(*bms) == oracle["and"], shape


@pytest.mark.parametrize("engine", ["xla", "pallas"])
def test_resident_set_parity(wide_case, engine):
    shape, bms, oracle = wide_case
    ds = aggregation.DeviceBitmapSet(bms)
    for op in ("or", "xor", "and"):
        assert ds.aggregate(op, engine=engine) == oracle[op], (shape, op)


@pytest.mark.parametrize("layout", ["dense", "compact"])
def test_chained_parity_at_extremes(wide_case, layout):
    # the chained steady-state probe (the benchmark measurement loop) must
    # stay bit-exact at segment-skew extremes too
    shape, bms, oracle = wide_case
    ds = aggregation.DeviceBitmapSet(bms, layout=layout)
    reps = 3
    got = int(np.asarray(ds.chained_wide_or(reps, engine="pallas")(ds.words)))
    assert got == (reps * oracle["or"].cardinality) % 2**32, (shape, layout)


def test_identical_inputs_share_every_key():
    # identical shape really is the one-giant-segment-per-key regime
    bms = make_wide("identical", "sparse", N, KEYS, seed=7)
    ds = aggregation.DeviceBitmapSet(bms)
    assert ds.keys.size == KEYS
    sizes = ds._packed.seg_sizes
    assert (sizes == N).all()


def test_disjoint_segments_are_singletons():
    bms = make_wide("disjoint", "sparse", N, KEYS, seed=7)
    ds = aggregation.DeviceBitmapSet(bms)
    assert (ds._packed.seg_sizes == 1).all()


PAIR_SHAPES = ["pair_bestcase", "pair_worstcase", "pair_identical"]


@pytest.mark.parametrize("shape", PAIR_SHAPES)
@pytest.mark.parametrize("op,host_op", [
    ("and", lambda x, y: x & y), ("or", lambda x, y: x | y),
    ("xor", lambda x, y: x ^ y), ("andnot", lambda x, y: x - y)])
def test_pairwise_stress_shapes(shape, op, host_op):
    # aggregation/{and,or,xor,andnot}/{bestcase,worstcase,identical}/
    # RoaringBitmapBenchmark.java — parity at the exact jmh pair layouts
    a, b = make_pair(shape)
    want = host_op(a, b)
    got = aggregation.pairwise(op, [(a, b)])[0]
    assert got == want, (shape, op)
    cards = aggregation.pairwise_cardinality(op, [(a, b)])
    assert int(cards[0]) == want.cardinality


@pytest.mark.parametrize("shape", PAIR_SHAPES)
def test_pair_bestcase_intersection_shapes(shape):
    # sanity-pin the layouts themselves (jmh setup invariants): bestcase AND
    # is tiny but non-empty only via the 50 near-miss keys; worstcase AND is
    # empty; identical AND equals either input
    a, b = make_pair(shape)
    inter = a & b
    if shape == "pair_bestcase":
        assert inter.cardinality == 0  # near-miss values differ by 13
        assert (a | b).cardinality == a.cardinality + b.cardinality
    elif shape == "pair_worstcase":
        assert inter.is_empty()
    else:
        assert inter == a == b
