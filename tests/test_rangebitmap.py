"""RangeBitmap tests — query parity against a NumPy oracle, appender
semantics, 0xF00D mappable serialization, and host/device bit-exactness
(mirrors RangeBitmapTest.java's threshold sweeps)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.bsi.device import DeviceRangeBitmap
from roaringbitmap_tpu.core.rangebitmap import Appender, RangeBitmap
from roaringbitmap_tpu.format.spec import InvalidRoaringFormat


@pytest.fixture(scope="module")
def values():
    rng = np.random.default_rng(0xF00D)
    return rng.integers(0, 1 << 40, 30000, dtype=np.uint64)


@pytest.fixture(scope="module")
def rbm(values):
    app = RangeBitmap.appender(int(values.max()))
    app.add_many(values)
    return app.build()


def _rows(mask):
    return np.flatnonzero(mask).astype(np.uint32)


class TestHostQueries:
    @pytest.mark.parametrize("q", [0.0, 0.1, 0.5, 0.9, 1.0])
    def test_threshold_sweep(self, values, rbm, q):
        t = int(np.quantile(values.astype(np.float64), q))
        assert np.array_equal(rbm.lte(t).to_array(), _rows(values <= t))
        assert np.array_equal(rbm.lt(t).to_array(), _rows(values < t))
        assert np.array_equal(rbm.gte(t).to_array(), _rows(values >= t))
        assert np.array_equal(rbm.gt(t).to_array(), _rows(values > t))

    def test_eq_neq(self, values, rbm):
        v = int(values[123])
        assert np.array_equal(rbm.eq(v).to_array(), _rows(values == v))
        assert np.array_equal(rbm.neq(v).to_array(), _rows(values != v))
        assert rbm.eq(int(values.max()) + 5).is_empty()
        assert rbm.neq(int(values.max()) + 5).cardinality == values.size

    def test_between(self, values, rbm):
        a = int(np.quantile(values.astype(np.float64), 0.3))
        b = int(np.quantile(values.astype(np.float64), 0.7))
        assert np.array_equal(rbm.between(a, b).to_array(),
                              _rows((values >= a) & (values <= b)))
        assert rbm.between_cardinality(a, b) == int(((values >= a) & (values <= b)).sum())

    def test_extremes(self, values, rbm):
        assert rbm.lte(int(values.max())).cardinality == values.size
        assert rbm.gte(0).cardinality == values.size
        assert rbm.lt(0).is_empty()
        assert rbm.gt(int(values.max())).is_empty()
        assert rbm.lte(2**63).cardinality == values.size  # above max

    def test_context(self, values, rbm):
        ctx = RoaringBitmap.from_values(
            np.arange(0, values.size, 7, dtype=np.uint32))
        t = int(np.median(values.astype(np.float64)))
        oracle = np.intersect1d(_rows(values <= t), ctx.to_array())
        assert np.array_equal(rbm.lte(t, ctx).to_array(), oracle)
        assert rbm.lte_cardinality(t, ctx) == oracle.size

    def test_context_out_of_range_rows(self, values, rbm):
        ctx = RoaringBitmap.from_values(
            np.array([0, 1, values.size + 100], dtype=np.uint32))
        got = rbm.neq(int(values[0]), ctx)
        assert values.size + 100 not in got

    def test_cardinality_forms(self, values, rbm):
        t = int(np.median(values.astype(np.float64)))
        assert rbm.lte_cardinality(t) == int((values <= t).sum())
        assert rbm.lt_cardinality(t) == int((values < t).sum())
        assert rbm.gte_cardinality(t) == int((values >= t).sum())
        assert rbm.gt_cardinality(t) == int((values > t).sum())


class TestAppender:
    def test_incremental_add(self):
        app = RangeBitmap.appender(1000)
        for v in (5, 900, 0, 1000):
            app.add(v)
        rb = app.build()
        assert rb.row_count == 4
        assert np.array_equal(rb.eq(900).to_array(), [1])
        assert np.array_equal(rb.lte(5).to_array(), [0, 2])

    def test_value_above_max_rejected(self):
        app = RangeBitmap.appender(100)
        with pytest.raises(ValueError):
            app.add(101)
        with pytest.raises(ValueError):
            app.add_many(np.array([5, 200], dtype=np.uint64))

    def test_clear_reuse(self):
        app = RangeBitmap.appender(50)
        app.add(10)
        app.clear()
        app.add(20)
        rb = app.build()
        assert rb.row_count == 1
        assert rb.eq(20).cardinality == 1
        assert rb.eq(10).is_empty()

    def test_build_twice_independent(self):
        app = RangeBitmap.appender(50)
        app.add(1)
        r1 = app.build()
        app.add(2)
        r2 = app.build()
        assert r1.row_count == 1 and r2.row_count == 2

    def test_zero_max_value(self):
        app = RangeBitmap.appender(0)
        app.add(0)
        rb = app.build()
        assert rb.lte(0).cardinality == 1
        assert rb.gt(0).is_empty()


class TestSerialization:
    def test_map_roundtrip(self, values, rbm):
        data = rbm.serialize()
        assert len(data) == rbm.serialized_size_in_bytes()
        back = RangeBitmap.map(data)
        assert back.row_count == rbm.row_count
        t = int(np.median(values.astype(np.float64)))
        assert back.lte(t) == rbm.lte(t)
        assert back.between(t // 2, t) == rbm.between(t // 2, t)

    def test_appender_serialize(self):
        app = RangeBitmap.appender(99)
        app.add_many(np.array([1, 50, 99], dtype=np.uint64))
        data = app.serialize()
        assert len(data) == app.serialized_size_in_bytes()
        rb = RangeBitmap.map(data)
        assert rb.row_count == 3

    def test_bad_cookie_rejected(self, rbm):
        data = bytearray(rbm.serialize())
        data[0] ^= 0xFF
        with pytest.raises(InvalidRoaringFormat):
            RangeBitmap.map(bytes(data))

    def test_truncated_rejected(self, rbm):
        with pytest.raises(InvalidRoaringFormat):
            RangeBitmap.map(rbm.serialize()[:10])


def _java_appender_stream(values: np.ndarray, max_value: int) -> bytes:
    """Independent emulation of the reference Appender's byte emission
    (RangeBitmap.java Appender.add :1514 / append :1545 / serialize :1483):
    complement bit slices per 2^16-row chunk, typed container records,
    per-chunk presence masks.  Deliberately NOT built on our RangeBitmap
    classes — this is the documented-layout fixture generator.

    Known limitation (ADVICE r2): both sides of this parity check come from
    the same reading of RangeBitmap.java — a shared misinterpretation would
    pass.  Java-produced fixture bytes cannot be generated in this image (no
    JVM, zero egress; the reference ships no serialized RangeBitmap fixtures
    under src/test/resources — only roaring-format .bin files, which
    tests/test_format.py already replays).  Mitigations here: the emulator is
    generated from the *spec text* (header <HBBHI, complement encoding, typed
    records, bit-length slice count per RangeBitmap.java:1491-1500,1622-1625)
    rather than from our encoder, and structural fields (cookie, slice count,
    record types) are asserted field-by-field, not only byte-equal."""
    import struct

    depth = max(int(max_value).bit_length(), 1)
    bpm = (depth + 7) >> 3
    rows = values.size
    n_keys = -(-rows // 65536)
    masks, records = bytearray(), bytearray()
    for key in range(n_keys):
        chunk = values[key << 16:(key + 1) << 16]
        mask_bits = 0
        recs = []
        for i in range(depth):
            # rows (within chunk) whose value has bit i CLEAR
            comp = np.flatnonzero(((chunk >> np.uint64(i)) & np.uint64(1)) == 0)
            if comp.size == 0:
                continue
            mask_bits |= 1 << i
            comp = comp.astype(np.uint16)
            diffs = np.diff(comp.astype(np.int64))
            n_runs = int(np.count_nonzero(diffs != 1)) + 1
            run_sz = 2 + 4 * n_runs
            # Java emission rule: slices < 5 are BitmapContainers in the
            # appender (containerForSlice) — runOptimize emits RUN only when
            # run beats 8192, never ARRAY (BitmapContainer.java:1218-1225);
            # slices >= 5 are RunContainers — toEfficientContainer keeps RUN
            # on <= ties vs min(8192, 2*card+2), else array/bitmap by card
            # (RunContainer.java:2326-2335)
            if i < 5:
                kind = 1 if run_sz < 8192 else 0
            elif run_sz <= min(8192, 2 * comp.size + 2):
                kind = 1
            elif comp.size <= 4096:
                kind = 2
            else:
                kind = 0
            rec = bytearray()
            if kind == 0:
                rec.append(0)
                rec += struct.pack("<H", comp.size & 0xFFFF)
                bits = np.zeros(1 << 16, np.uint8)
                bits[comp] = 1
                rec += np.packbits(bits, bitorder="little").tobytes()
            elif kind == 1:
                rec.append(1)
                breaks = np.flatnonzero(diffs != 1)
                starts = np.concatenate(([0], breaks + 1))
                stops = np.concatenate((breaks, [comp.size - 1]))
                rec += struct.pack("<H", starts.size)
                pairs = np.empty(2 * starts.size, np.uint16)
                pairs[0::2] = comp[starts]
                pairs[1::2] = comp[stops] - comp[starts]
                rec += pairs.astype("<u2").tobytes()
            else:
                rec.append(2)
                rec += struct.pack("<H", comp.size)
                rec += comp.astype("<u2").tobytes()
            recs.append(bytes(rec))
        masks += mask_bits.to_bytes(bpm, "little")
        records += b"".join(recs)
    head = struct.pack("<HBBHI", 0xF00D, 2, depth, n_keys, rows)
    return head + bytes(masks) + bytes(records)


class TestReferenceLayout:
    """VERDICT r1 item 7: reference-produced streams must load and answer
    bit-exactly."""

    @pytest.fixture(scope="class")
    def ref_values(self):
        rng = np.random.default_rng(42)
        # mix: uniform + clustered + constant tail spanning >1 chunk
        v = np.concatenate([
            rng.integers(0, 1 << 20, 70000, dtype=np.uint64),
            np.full(5000, 12345, dtype=np.uint64),
            rng.integers(0, 64, 8000, dtype=np.uint64),
        ])
        return v

    def test_mapped_reference_stream_queries(self, ref_values):
        stream = _java_appender_stream(ref_values, int(ref_values.max()))
        rbm = RangeBitmap.map(stream)
        assert rbm.row_count == ref_values.size
        for q in (0, 17, 63, 12345, 100000, int(ref_values.max())):
            assert np.array_equal(rbm.lte(q).to_array(),
                                  _rows(ref_values <= q)), q
            assert np.array_equal(rbm.gt(q).to_array(),
                                  _rows(ref_values > q)), q
            assert np.array_equal(rbm.eq(q).to_array(),
                                  _rows(ref_values == q)), q
        assert np.array_equal(
            rbm.between(100, 12345).to_array(),
            _rows((ref_values >= 100) & (ref_values <= 12345)))

    def test_our_serialize_parses_as_reference_layout(self, ref_values):
        """Our serializer and the independent emulator produce identical
        bytes for the same input (container-type rules included)."""
        app = RangeBitmap.appender(int(ref_values.max()))
        app.add_many(ref_values)
        ours = app.build().serialize()
        theirs = _java_appender_stream(ref_values, int(ref_values.max()))
        assert ours == theirs

    def test_full_and_empty_chunk_edges(self):
        # constant zeros: every slice complement is full -> run containers
        v = np.zeros(70000, dtype=np.uint64)
        stream = _java_appender_stream(v, 100)
        rbm = RangeBitmap.map(stream)
        assert rbm.lte(0).cardinality == v.size
        assert rbm.gt(0).is_empty()
        ours = RangeBitmap.appender(100)
        ours.add_many(v)
        assert ours.serialize() == stream


class TestDeviceRangeBitmap:
    @pytest.fixture(scope="class")
    def dev(self, rbm):
        return DeviceRangeBitmap(rbm)

    @pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.75, 1.0])
    def test_device_matches_host(self, values, rbm, dev, q):
        t = int(np.quantile(values.astype(np.float64), q))
        assert dev.lte(t) == rbm.lte(t)
        assert dev.lt(t) == rbm.lt(t)
        assert dev.gte(t) == rbm.gte(t)
        assert dev.gt(t) == rbm.gt(t)

    def test_device_eq_neq_between(self, values, rbm, dev):
        v = int(values[55])
        assert dev.eq(v) == rbm.eq(v)
        assert dev.neq(v) == rbm.neq(v)
        a = int(np.quantile(values.astype(np.float64), 0.4))
        b = int(np.quantile(values.astype(np.float64), 0.6))
        assert dev.between(a, b) == rbm.between(a, b)

    def test_device_context(self, values, rbm, dev):
        ctx = RoaringBitmap.from_values(
            np.arange(0, values.size, 11, dtype=np.uint32))
        t = int(np.median(values.astype(np.float64)))
        assert dev.lte(t, ctx) == rbm.lte(t, ctx)
        assert dev.neq(int(values[3]), ctx) == rbm.neq(int(values[3]), ctx)
        assert dev.between_cardinality(t // 2, t, ctx) == \
            rbm.between_cardinality(t // 2, t, ctx)

    def test_device_context_out_of_range(self, values, rbm, dev):
        ctx = RoaringBitmap.from_values(
            np.array([0, 1, values.size + 100], dtype=np.uint32))
        v = int(values[0])
        assert dev.neq(v, ctx) == rbm.neq(v, ctx)

    def test_device_guards(self, values, rbm, dev):
        assert dev.lte(2**63) == rbm.lte(2**63)
        assert dev.gte(0) == rbm.gte(0)
        assert dev.lt(0).is_empty()
        assert dev.gt(int(values.max())).is_empty()
        assert dev.eq(int(values.max()) + 5).is_empty()
        assert dev.neq(int(values.max()) + 5).cardinality == values.size
