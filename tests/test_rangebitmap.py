"""RangeBitmap tests — query parity against a NumPy oracle, appender
semantics, 0xF00D mappable serialization, and host/device bit-exactness
(mirrors RangeBitmapTest.java's threshold sweeps)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.bsi.device import DeviceRangeBitmap
from roaringbitmap_tpu.core.rangebitmap import Appender, RangeBitmap
from roaringbitmap_tpu.format.spec import InvalidRoaringFormat


@pytest.fixture(scope="module")
def values():
    rng = np.random.default_rng(0xF00D)
    return rng.integers(0, 1 << 40, 30000, dtype=np.uint64)


@pytest.fixture(scope="module")
def rbm(values):
    app = RangeBitmap.appender(int(values.max()))
    app.add_many(values)
    return app.build()


def _rows(mask):
    return np.flatnonzero(mask).astype(np.uint32)


class TestHostQueries:
    @pytest.mark.parametrize("q", [0.0, 0.1, 0.5, 0.9, 1.0])
    def test_threshold_sweep(self, values, rbm, q):
        t = int(np.quantile(values.astype(np.float64), q))
        assert np.array_equal(rbm.lte(t).to_array(), _rows(values <= t))
        assert np.array_equal(rbm.lt(t).to_array(), _rows(values < t))
        assert np.array_equal(rbm.gte(t).to_array(), _rows(values >= t))
        assert np.array_equal(rbm.gt(t).to_array(), _rows(values > t))

    def test_eq_neq(self, values, rbm):
        v = int(values[123])
        assert np.array_equal(rbm.eq(v).to_array(), _rows(values == v))
        assert np.array_equal(rbm.neq(v).to_array(), _rows(values != v))
        assert rbm.eq(int(values.max()) + 5).is_empty()
        assert rbm.neq(int(values.max()) + 5).cardinality == values.size

    def test_between(self, values, rbm):
        a = int(np.quantile(values.astype(np.float64), 0.3))
        b = int(np.quantile(values.astype(np.float64), 0.7))
        assert np.array_equal(rbm.between(a, b).to_array(),
                              _rows((values >= a) & (values <= b)))
        assert rbm.between_cardinality(a, b) == int(((values >= a) & (values <= b)).sum())

    def test_extremes(self, values, rbm):
        assert rbm.lte(int(values.max())).cardinality == values.size
        assert rbm.gte(0).cardinality == values.size
        assert rbm.lt(0).is_empty()
        assert rbm.gt(int(values.max())).is_empty()
        assert rbm.lte(2**63).cardinality == values.size  # above max

    def test_context(self, values, rbm):
        ctx = RoaringBitmap.from_values(
            np.arange(0, values.size, 7, dtype=np.uint32))
        t = int(np.median(values.astype(np.float64)))
        oracle = np.intersect1d(_rows(values <= t), ctx.to_array())
        assert np.array_equal(rbm.lte(t, ctx).to_array(), oracle)
        assert rbm.lte_cardinality(t, ctx) == oracle.size

    def test_context_out_of_range_rows(self, values, rbm):
        ctx = RoaringBitmap.from_values(
            np.array([0, 1, values.size + 100], dtype=np.uint32))
        got = rbm.neq(int(values[0]), ctx)
        assert values.size + 100 not in got

    def test_cardinality_forms(self, values, rbm):
        t = int(np.median(values.astype(np.float64)))
        assert rbm.lte_cardinality(t) == int((values <= t).sum())
        assert rbm.lt_cardinality(t) == int((values < t).sum())
        assert rbm.gte_cardinality(t) == int((values >= t).sum())
        assert rbm.gt_cardinality(t) == int((values > t).sum())


class TestAppender:
    def test_incremental_add(self):
        app = RangeBitmap.appender(1000)
        for v in (5, 900, 0, 1000):
            app.add(v)
        rb = app.build()
        assert rb.row_count == 4
        assert np.array_equal(rb.eq(900).to_array(), [1])
        assert np.array_equal(rb.lte(5).to_array(), [0, 2])

    def test_value_above_max_rejected(self):
        app = RangeBitmap.appender(100)
        with pytest.raises(ValueError):
            app.add(101)
        with pytest.raises(ValueError):
            app.add_many(np.array([5, 200], dtype=np.uint64))

    def test_clear_reuse(self):
        app = RangeBitmap.appender(50)
        app.add(10)
        app.clear()
        app.add(20)
        rb = app.build()
        assert rb.row_count == 1
        assert rb.eq(20).cardinality == 1
        assert rb.eq(10).is_empty()

    def test_build_twice_independent(self):
        app = RangeBitmap.appender(50)
        app.add(1)
        r1 = app.build()
        app.add(2)
        r2 = app.build()
        assert r1.row_count == 1 and r2.row_count == 2

    def test_zero_max_value(self):
        app = RangeBitmap.appender(0)
        app.add(0)
        rb = app.build()
        assert rb.lte(0).cardinality == 1
        assert rb.gt(0).is_empty()


class TestSerialization:
    def test_map_roundtrip(self, values, rbm):
        data = rbm.serialize()
        assert len(data) == rbm.serialized_size_in_bytes()
        back = RangeBitmap.map(data)
        assert back.row_count == rbm.row_count
        t = int(np.median(values.astype(np.float64)))
        assert back.lte(t) == rbm.lte(t)
        assert back.between(t // 2, t) == rbm.between(t // 2, t)

    def test_appender_serialize(self):
        app = RangeBitmap.appender(99)
        app.add_many(np.array([1, 50, 99], dtype=np.uint64))
        data = app.serialize()
        assert len(data) == app.serialized_size_in_bytes()
        rb = RangeBitmap.map(data)
        assert rb.row_count == 3

    def test_bad_cookie_rejected(self, rbm):
        data = bytearray(rbm.serialize())
        data[0] ^= 0xFF
        with pytest.raises(InvalidRoaringFormat):
            RangeBitmap.map(bytes(data))

    def test_truncated_rejected(self, rbm):
        with pytest.raises(InvalidRoaringFormat):
            RangeBitmap.map(rbm.serialize()[:10])


class TestDeviceRangeBitmap:
    @pytest.fixture(scope="class")
    def dev(self, rbm):
        return DeviceRangeBitmap(rbm)

    @pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.75, 1.0])
    def test_device_matches_host(self, values, rbm, dev, q):
        t = int(np.quantile(values.astype(np.float64), q))
        assert dev.lte(t) == rbm.lte(t)
        assert dev.lt(t) == rbm.lt(t)
        assert dev.gte(t) == rbm.gte(t)
        assert dev.gt(t) == rbm.gt(t)

    def test_device_eq_neq_between(self, values, rbm, dev):
        v = int(values[55])
        assert dev.eq(v) == rbm.eq(v)
        assert dev.neq(v) == rbm.neq(v)
        a = int(np.quantile(values.astype(np.float64), 0.4))
        b = int(np.quantile(values.astype(np.float64), 0.6))
        assert dev.between(a, b) == rbm.between(a, b)

    def test_device_context(self, values, rbm, dev):
        ctx = RoaringBitmap.from_values(
            np.arange(0, values.size, 11, dtype=np.uint32))
        t = int(np.median(values.astype(np.float64)))
        assert dev.lte(t, ctx) == rbm.lte(t, ctx)
        assert dev.neq(int(values[3]), ctx) == rbm.neq(int(values[3]), ctx)
        assert dev.between_cardinality(t // 2, t, ctx) == \
            rbm.between_cardinality(t // 2, t, ctx)

    def test_device_context_out_of_range(self, values, rbm, dev):
        ctx = RoaringBitmap.from_values(
            np.array([0, 1, values.size + 100], dtype=np.uint32))
        v = int(values[0])
        assert dev.neq(v, ctx) == rbm.neq(v, ctx)

    def test_device_guards(self, values, rbm, dev):
        assert dev.lte(2**63) == rbm.lte(2**63)
        assert dev.gte(0) == rbm.gte(0)
        assert dev.lt(0).is_empty()
        assert dev.gt(int(values.max())).is_empty()
        assert dev.eq(int(values.max()) + 5).is_empty()
        assert dev.neq(int(values.max()) + 5).cardinality == values.size
