"""Hardened query runtime: taxonomy, fault injection, guarded dispatch.

The acceptance matrix of the robustness tentpole: under injected faults
(fixed seeds, every error class, each engine rung) every batched query
either returns a result bit-exact with the CPU sequential reference or
raises a typed runtime.errors exception — zero silent corruption, zero
bare RuntimeError/ValueError escapes."""

import time

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import (BatchEngine, BatchQuery,
                                        aggregation, sharding)
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.runtime.cache import LRUCache

#: no real sleeping inside the suite; retries still count attempts
NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)

N = 12


@pytest.fixture(scope="module")
def workload():
    rng = np.random.default_rng(0xBEEF)
    common = np.arange(300, 700, dtype=np.uint32)
    out = []
    for i in range(N):
        vals = [rng.integers(0, 1 << 17, 2500).astype(np.uint32), common]
        if i % 4 == 0:
            vals.append(np.arange(1 << 16, (1 << 16) + 15000,
                                  dtype=np.uint32))
        out.append(RoaringBitmap.from_values(
            np.unique(np.concatenate(vals))))
    return out


@pytest.fixture(scope="module")
def engine(workload):
    return BatchEngine.from_bitmaps(workload)


def _queries(q, form="cardinality", seed=11):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(q):
        op = ("or", "and", "xor", "andnot")[i % 4]
        k = int(rng.integers(2, 7))
        out.append(BatchQuery(
            op=op, operands=tuple(
                int(x) for x in rng.choice(N, size=k, replace=False)),
            form=form))
    return out


# ------------------------------------------------------------ errors.classify

class TestClassify:
    @pytest.mark.parametrize("msg,cls", [
        ("RESOURCE_EXHAUSTED: out of memory allocating 8388608 bytes",
         errors.ResourceExhausted),
        ("XlaRuntimeError: UNAVAILABLE: device connection dropped",
         errors.TransientDeviceError),
        ("DEADLINE_EXCEEDED: something slow", errors.TransientDeviceError),
        ("INTERNAL: coordination service barrier timed out",
         errors.CoordinatorTimeout),
    ])
    def test_message_families(self, msg, cls):
        assert isinstance(errors.classify(RuntimeError(msg)), cls)

    def test_lowering_by_message_not_type(self):
        assert isinstance(
            errors.classify(NotImplementedError("Mosaic lowering failed")),
            errors.EngineLoweringError)
        assert isinstance(
            errors.classify(RuntimeError("Mosaic lowering failed")),
            errors.EngineLoweringError)
        # a stubbed host method is a programming error, not a demotable
        # engine fault — the blanket NotImplementedError match was a bug
        assert errors.classify(NotImplementedError("todo")) is None

    def test_corrupt_input_identity(self):
        e = errors.CorruptInput("bad cookie")
        assert errors.classify(e) is e

    def test_typed_passthrough_is_idempotent(self):
        e = errors.ResourceExhausted("oom")
        assert errors.classify(e) is e

    def test_programming_errors_are_not_classified(self):
        assert errors.classify(IndexError("operand out of range")) is None
        assert errors.classify(KeyError("x")) is None
        assert errors.classify(ValueError("plain bad arg")) is None

    def test_keyword_brushes_stay_unclassified(self):
        # genuine bugs whose messages merely brush a fault keyword must
        # stay raw — lowercase 'aborted'/'oom'/'coordinator' are not
        # status tokens (only the uppercase absl forms are)
        for msg in ("scan aborted: invalid plan state",
                    "cannot open /data/zoom_datasets/x.bin",
                    "bad coordinator_address argument type",
                    "value cancelled_flag must be bool"):
            assert errors.classify(RuntimeError(msg)) is None, msg


# ---------------------------------------------------------------- fault spec

class TestFaultSpec:
    def test_grammar(self):
        plan = faults.FaultPlan.from_spec(
            "transient=0.5,oom@pallas,lowering@batch_engine=0.25:42")
        kinds = [(r.kind, r.scope, r.rate) for r in plan.rules]
        assert kinds == [("transient", None, 0.5), ("oom", "pallas", 1.0),
                         ("lowering", "batch_engine", 0.25)]
        assert plan.seed == 42

    @pytest.mark.parametrize("bad", [
        "transient=0.5", "nosuchkind:3", "transient=2.0:3",
        "transient=x:3", ":", "  :9",
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            faults.FaultPlan.from_spec(bad)

    def test_scoped_rule_only_fires_in_scope(self):
        plan = faults.FaultPlan.from_spec("oom@pallas:1")
        assert plan.pick("batch_engine", "pallas") == "oom"
        assert plan.pick("batch_engine", "xla") is None
        assert plan.pick("pallas", None) == "oom"   # site name also matches

    def test_deterministic_schedule(self):
        draws = []
        for _ in range(2):
            plan = faults.FaultPlan.from_spec("transient=0.3:99")
            draws.append([plan.pick("s", "e") for _ in range(64)])
        assert draws[0] == draws[1]
        assert "transient" in draws[0]          # some fire
        assert draws[0].count(None) > 0         # some do not

    def test_silent_separated_from_raising(self):
        plan = faults.FaultPlan.from_spec("silent:5")
        assert plan.pick("s", "e") is None      # raising picker skips it
        assert plan.pick("s", "e", kinds=("silent",)) == "silent"

    def test_inject_overrides_and_restores(self):
        prev = faults.active()      # None, or the CI fault shard's env plan
        with faults.inject("oom:1") as plan:
            assert faults.active() is plan
        assert faults.active() is prev

    def test_slow_grammar_and_separation(self):
        plan = faults.FaultPlan.from_spec("slow@serving=0.25,slow:7")
        kinds = [(r.kind, r.scope, r.rate) for r in plan.rules]
        assert kinds == [("slow", "serving", 0.25), ("slow", None, 1.0)]
        # the raising picker skips slow rules entirely — maybe_fail can
        # never raise from injected latency
        assert plan.pick("s", "e") is None
        assert plan.pick("s", "e", kinds=("slow",)) == "slow"
        with faults.inject("slow:3"):
            faults.maybe_fail("s", "e")              # must not raise


class TestFaultClock:
    def setup_method(self):
        faults.reset_clock()

    def teardown_method(self):
        faults.reset_clock()

    def test_clock_advances_without_sleeping(self):
        t0 = faults.clock()
        w0 = time.monotonic()
        faults.advance_clock(2.5)
        assert faults.clock() - t0 >= 2.5
        assert time.monotonic() - w0 < 1.0           # no real waiting
        faults.advance_clock(-5.0)                    # never backwards
        assert faults.clock() - t0 >= 2.5

    def test_maybe_delay_is_deterministic(self):
        seen = []
        for _ in range(2):
            faults.reset_clock()
            with faults.inject("slow=0.3:99"):
                seen.append([faults.maybe_delay("s", "e")
                             for _ in range(32)])
        assert seen[0] == seen[1]
        fired = [d for d in seen[0] if d]
        assert fired and all(d == faults.SLOW_LATENCY_S for d in fired)
        assert len(fired) < 32                       # rate < 1 skips some

    def test_deadline_expires_on_the_fault_clock(self):
        dl = guard.Deadline(0.2)
        assert not dl.expired() and dl.remaining() > 0
        faults.advance_clock(0.5)
        assert dl.expired() and dl.remaining() == 0.0

    def test_slow_injection_exhausts_guard_deadline_typed(self):
        """Every attempt burns SLOW_LATENCY_S of virtual time before the
        expiry check, so a sub-quantum deadline dies typed on the first
        rung — no wall clock involved."""
        calls = []
        with faults.inject("slow@t=1.0:4"):
            with pytest.raises(errors.TransientDeviceError,
                               match="deadline"):
                guard.run_with_fallback(
                    "t", ("e1",), lambda e: calls.append(e),
                    policy=guard.GuardPolicy(
                        deadline=faults.SLOW_LATENCY_S / 2,
                        backoff_base=0.0, sleep=lambda s: None))
        assert calls == []                   # expired before any attempt

    def test_for_remaining_derives_guard_deadline(self):
        base = guard.GuardPolicy(deadline=10.0, slo_deadline_ms=9000.0)
        p = base.for_remaining(0.5)
        assert p.deadline == 0.5 and p.slo_deadline_ms == 500.0
        assert p.max_attempts == base.max_attempts   # only deadlines move
        open_ = guard.GuardPolicy().for_remaining(2.0)
        assert open_.deadline == 2.0 and open_.slo_deadline_ms == 2000.0


# ----------------------------------------------------------------- LRU cache

class TestLRUCache:
    def test_eviction_order_and_stats(self):
        c = LRUCache(2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1          # refresh a
        c.put("c", 3)                   # evicts b (LRU)
        assert c.get("b") is None
        assert c.get("a") == 1 and c.get("c") == 3
        s = c.stats()
        assert s["evictions"] == 1 and s["size"] == 2
        assert s["hits"] == 3 and s["misses"] == 1

    def test_clear_and_contains(self):
        c = LRUCache(4)
        c.put("k", "v")
        assert "k" in c and len(c) == 1
        c.clear()
        assert "k" not in c and len(c) == 0

    def test_min_size(self):
        with pytest.raises(ValueError):
            LRUCache(0)


# ------------------------------------------------------------ guard unit set

class TestGuard:
    def test_transient_retries_then_succeeds(self):
        calls = []

        def attempt(eng):
            calls.append(eng)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: flaky")
            return "ok"

        res, rung = guard.run_with_fallback(
            "t", ("e1", "e2"), attempt, policy=NOSLEEP)
        assert res == "ok" and rung == "e1" and calls == ["e1"] * 3

    def test_retry_exhaustion_demotes(self):
        calls = []

        def attempt(eng):
            calls.append(eng)
            if eng == "e1":
                raise RuntimeError("UNAVAILABLE: always down")
            return "ok"

        res, rung = guard.run_with_fallback(
            "t", ("e1", "e2"), attempt, policy=NOSLEEP)
        assert rung == "e2" and calls == ["e1"] * 3 + ["e2"]

    def test_lowering_demotes_immediately(self):
        calls = []

        def attempt(eng):
            calls.append(eng)
            if eng == "e1":
                raise NotImplementedError("Mosaic lowering failed")
            return "ok"

        res, rung = guard.run_with_fallback(
            "t", ("e1", "e2"), attempt, policy=NOSLEEP)
        assert rung == "e2" and calls == ["e1", "e2"]

    def test_oom_offers_split_first(self):
        def attempt(eng):
            raise RuntimeError("RESOURCE_EXHAUSTED: oom")

        def split(eng, fault, dl):
            assert isinstance(fault, errors.ResourceExhausted)
            return "halved"

        res, rung = guard.run_with_fallback(
            "t", ("e1",), attempt, policy=NOSLEEP,
            on_resource_exhausted=split)
        assert res == "halved"

    def test_oom_split_declined_demotes(self):
        seen = []

        def attempt(eng):
            seen.append(eng)
            if eng == "e1":
                raise RuntimeError("RESOURCE_EXHAUSTED: oom")
            return "ok"

        res, rung = guard.run_with_fallback(
            "t", ("e1", "e2"), attempt, policy=NOSLEEP,
            on_resource_exhausted=lambda *a: guard.NO_SPLIT)
        assert rung == "e2" and seen == ["e1", "e2"]

    def test_corrupt_input_is_fatal_immediately(self):
        calls = []

        def attempt(eng):
            calls.append(eng)
            raise errors.CorruptInput("bad payload")

        with pytest.raises(errors.CorruptInput):
            guard.run_with_fallback("t", ("e1", "e2"), attempt,
                                    policy=NOSLEEP,
                                    sequential=lambda: "never")
        assert calls == ["e1"]

    def test_unclassified_exceptions_propagate_raw(self):
        def attempt(eng):
            raise IndexError("planner bug")

        with pytest.raises(IndexError):
            guard.run_with_fallback("t", ("e1", "e2"), attempt,
                                    policy=NOSLEEP,
                                    sequential=lambda: "never")

    def test_exhausted_chain_raises_typed(self):
        def attempt(eng):
            raise RuntimeError("UNAVAILABLE: dead device")

        with pytest.raises(errors.TransientDeviceError):
            guard.run_with_fallback("t", ("e1", "e2"), attempt,
                                    policy=NOSLEEP)

    def test_deadline_respected(self):
        t0 = time.monotonic()
        policy = guard.GuardPolicy(max_attempts=10_000,
                                   backoff_base=0.005, deadline=0.15)

        def attempt(eng):
            raise RuntimeError("UNAVAILABLE: flaky forever")

        with pytest.raises(errors.TransientDeviceError) as ei:
            guard.run_with_fallback("t", ("e1", "e2"), attempt,
                                    policy=policy,
                                    sequential=lambda: "unreached")
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0                       # stopped promptly
        assert "deadline" in str(ei.value)

    def test_dispatch_stats_count_degradation(self):
        guard.reset_dispatch_stats()

        def attempt(eng):
            raise RuntimeError("UNAVAILABLE: flaky")

        res, rung = guard.run_with_fallback(
            "statsite", ("e1",), attempt, policy=NOSLEEP,
            sequential=lambda: "floor")
        assert (res, rung) == ("floor", guard.SEQUENTIAL)
        s = guard.dispatch_stats("statsite")
        assert s["retries"] == 2       # 3 attempts = 2 retries
        assert s["demotions"] == 1 and s["sequential"] == 1
        # site isolation + copy semantics
        assert guard.dispatch_stats("othersite")["retries"] == 0
        guard.dispatch_stats("statsite")["retries"] = 99
        assert guard.dispatch_stats("statsite")["retries"] == 2

    def test_chain_from(self):
        ladder = ("pallas", "xla", "xla-vmap")
        assert guard.chain_from("pallas", ladder) == (
            "pallas", "xla", "xla-vmap", guard.SEQUENTIAL)
        assert guard.chain_from("xla-vmap", ladder) == (
            "xla-vmap", guard.SEQUENTIAL)
        assert guard.chain_from("weird", ladder) == (
            "weird", guard.SEQUENTIAL)


# ------------------------------------- fallback chain: the acceptance matrix

RUNGS = ("pallas", "xla", "xla-vmap")


class TestBatchFallbackChain:
    @pytest.mark.parametrize("rung", RUNGS)
    @pytest.mark.parametrize("kind", ("transient", "oom", "lowering"))
    def test_fault_at_each_rung_stays_bit_exact(self, engine, rung, kind):
        queries = _queries(10, form="bitmap", seed=ord(kind[0]))
        want = engine._execute_sequential(queries)
        with faults.inject(f"{kind}@{rung}=1.0:17"):
            got = engine.execute(queries, engine=rung, policy=NOSLEEP)
        for q, g, w in zip(queries, got, want):
            assert g.cardinality == w.cardinality, (rung, kind, q)
            assert g.bitmap == w.bitmap, (rung, kind, q)

    @pytest.mark.parametrize("rung", RUNGS)
    def test_corrupt_input_raises_typed_at_each_rung(self, engine, rung):
        with faults.inject(f"corrupt@{rung}=1.0:17"):
            with pytest.raises(errors.CorruptInput):
                engine.execute(_queries(4), engine=rung, policy=NOSLEEP)

    def test_every_engine_down_degrades_to_sequential(self, engine):
        queries = _queries(9, form="bitmap", seed=5)
        want = engine._execute_sequential(queries)
        with faults.inject("lowering=1.0:23"):
            got = engine.execute(queries, engine="pallas", policy=NOSLEEP)
        assert [g.cardinality for g in got] == [w.cardinality for w in want]
        assert all(g.bitmap == w.bitmap for g, w in zip(got, want))

    def test_oom_splits_batch_and_stays_exact(self, engine):
        queries = _queries(16, seed=31)
        want = [w.cardinality for w in engine._execute_sequential(queries)]
        before = engine.split_count
        with faults.inject("oom@xla=1.0:31"):
            got = engine.execute(queries, engine="xla", policy=NOSLEEP)
        assert [g.cardinality for g in got] == want
        assert engine.split_count > before   # halving really happened

    def test_partial_oom_recovers_without_demotion(self, engine):
        # 30% OOM rate: some (sub)batches split, everything stays exact
        queries = _queries(12, seed=41)
        want = [w.cardinality for w in engine._execute_sequential(queries)]
        with faults.inject("oom@xla=0.3:41"):
            got = engine.execute(queries, engine="xla", policy=NOSLEEP)
        assert [g.cardinality for g in got] == want

    def test_deadline_bounds_batch_dispatch(self, engine):
        policy = guard.GuardPolicy(max_attempts=10_000, backoff_base=0.005,
                                   deadline=0.2)
        t0 = time.monotonic()
        with faults.inject("transient=1.0:13"):
            with pytest.raises(errors.TransientDeviceError):
                engine.execute(_queries(4), engine="xla", policy=policy)
        assert time.monotonic() - t0 < 10.0

    def test_shadow_catches_silent_corruption(self, engine):
        shadow = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None,
                                   shadow_rate=1.0)
        with faults.inject("silent@batch_engine=1.0:3"):
            with pytest.raises(errors.ShadowMismatch):
                engine.execute(_queries(6), engine="xla", policy=shadow)

    def test_silent_fault_without_shadow_proves_the_knob_matters(self, engine):
        # the harness really corrupts: without the shadow check the wrong
        # answer sails through — that asymmetry is the knob's reason to exist
        queries = _queries(6, seed=3)
        want = engine._execute_sequential(queries)
        with faults.inject("silent@batch_engine=1.0:3"):
            got = engine.execute(queries, engine="xla", policy=NOSLEEP)
        assert got[0].cardinality == want[0].cardinality + 1

    def test_no_faults_no_behavior_change(self, engine):
        queries = _queries(8, form="bitmap", seed=77)
        want = engine._execute_sequential(queries)
        got = engine.execute(queries, engine="xla")
        assert all(g.bitmap == w.bitmap for g, w in zip(got, want))

    def test_validation_errors_stay_raw(self, engine):
        # programming errors must NOT be converted or degraded
        with pytest.raises(IndexError):
            engine.execute([BatchQuery("or", (0, N + 5))], policy=NOSLEEP)

    def test_fallback_false_paths_skip_injection(self, engine, workload):
        """The raw escape hatch means raw: with every fault kind firing at
        rate 1.0, fallback=False paths neither raise injected faults nor
        return corrupted results — pinned parity probes stay deterministic
        under the CI fault shard's environment."""
        queries = _queries(6, seed=61)
        want = [w.cardinality for w in engine._execute_sequential(queries)]
        ref_or = aggregation._sequential_reduce("or", workload)
        with faults.inject(
                "transient=1.0,oom=1.0,lowering=1.0,corrupt=1.0,"
                "silent=1.0:9"):
            got = engine.execute(queries, engine="xla", fallback=False)
            assert [g.cardinality for g in got] == want
            assert aggregation.or_(*workload, engine="xla",
                                   fallback=False) == ref_or
            assert aggregation.or_cardinality(
                *workload, fallback=False) == ref_or.cardinality
            assert aggregation.and_cardinality(*workload, fallback=False) \
                == aggregation._sequential_reduce("and",
                                                  workload).cardinality


class TestBatchEngineCaches:
    def test_cache_stats_exposed(self, workload):
        eng = BatchEngine.from_bitmaps(workload)
        eng.execute(_queries(4, seed=1), engine="xla")
        s = eng.cache_stats()
        assert s["plans"]["misses"] >= 1
        assert s["programs"]["size"] >= 1
        eng.execute(_queries(4, seed=1), engine="xla")
        assert eng.cache_stats()["plans"]["hits"] >= 1

    def test_plan_cache_bounded_with_eviction_counter(self, workload):
        from roaringbitmap_tpu.runtime.cache import LRUCache as LC

        eng = BatchEngine.from_bitmaps(workload)
        eng._plans = LC(2)
        for seed in range(4):     # 4 distinct batch shapes, cap 2
            eng.execute(_queries(2, seed=100 + seed), engine="xla")
        s = eng.cache_stats()["plans"]
        assert s["size"] <= 2 and s["evictions"] >= 2


# ----------------------------------------- aggregation + sharding degradation

class TestWideDegradation:
    def test_wide_ops_degrade_bit_exact(self, workload):
        ref_or = aggregation._sequential_reduce("or", workload)
        ref_xor = aggregation._sequential_reduce("xor", workload)
        ref_and = aggregation._sequential_reduce("and", workload)
        with faults.inject("lowering=1.0:19"):
            assert aggregation.or_(*workload, engine="xla") == ref_or
            assert aggregation.xor(*workload, engine="xla") == ref_xor
            assert aggregation.and_(*workload) == ref_and

    def test_wide_cardinalities_degrade(self, workload):
        want = aggregation._sequential_reduce("or", workload).cardinality
        with faults.inject("transient@aggregation=1.0:19"):
            assert aggregation.or_cardinality(*workload) == want

    def test_sharded_degrades_to_sequential(self, workload):
        import jax
        from jax.sharding import Mesh

        from roaringbitmap_tpu.ops import packing

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2),
                    ("rows", "lanes"))
        want = aggregation._sequential_reduce("or", workload)
        with faults.inject("transient@sharded=1.0:29"):
            k, w, c = sharding.wide_aggregate_sharded(mesh, "or", workload)
        assert packing.unpack_result(k, w, c) == want

    def test_sharded_corrupt_input_typed(self, workload):
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2),
                    ("rows", "lanes"))
        with faults.inject("corrupt@sharding=1.0:29"):
            with pytest.raises(errors.CorruptInput):
                sharding.wide_aggregate_sharded(mesh, "or", workload)
