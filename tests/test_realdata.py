"""Real-dataset end-to-end parity (the realdata JMH correctness-test analog,
jmh/src/test/.../realdata/*Test.java): wide ops over census1881 must match
the NumPy oracle exactly."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import aggregation
from roaringbitmap_tpu.utils import datasets

pytestmark = pytest.mark.skipif(
    not datasets.has_dataset("census1881"), reason="reference datasets not mounted")


@pytest.fixture(scope="module")
def census():
    return datasets.load_value_arrays("census1881")


def test_wide_or_census1881_bit_exact(census):
    arrs = census[:64]  # keep CPU-test runtime modest; bench runs the full set
    bms = [RoaringBitmap.from_values(a) for a in arrs]
    oracle = np.unique(np.concatenate(arrs))
    got = aggregation.or_(bms, engine="xla", fallback=False)
    assert got.cardinality == oracle.size
    np.testing.assert_array_equal(got.to_array(), oracle)
    got_p = aggregation.or_(bms, engine="pallas", fallback=False)
    assert got_p == got


def test_wide_and_census1881(census):
    arrs = census[:8]
    bms = [RoaringBitmap.from_values(a) for a in arrs]
    oracle = set(arrs[0].tolist())
    for a in arrs[1:]:
        oracle &= set(a.tolist())
    got = aggregation.and_(bms)
    assert set(got.to_array().tolist()) == oracle


def test_serialization_of_device_result(census):
    arrs = census[:32]
    bms = [RoaringBitmap.from_values(a) for a in arrs]
    got = aggregation.or_(bms, engine="xla")
    raw = got.serialize()
    assert RoaringBitmap.deserialize(raw) == got
