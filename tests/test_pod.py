"""Pod-scale data plane (parallel/podmesh.py + serving/frontdoor.py,
docs/POD.md): tenant placement regimes, consistent routing, mis-route
forwarding, cross-host fair share, host-drop degradation through the
``reroute`` rung, the threaded pump driver, the async maintenance
worker, and the 2-process CPU-cluster bring-up (tests/test_multihost.py
extended — placement/routing agreement across real processes, each host
feeding only its addressable shard).

The in-process tests run a SIMULATED pod over the suite's 8 virtual CPU
devices — the same dry-run strategy as the sharded engine's mesh tests;
cross-process collective dispatch needs a real TPU pod backend and rides
the standing TPU debt (``podmesh.supports_pod_dispatch``)."""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                        MultiSetBatchEngine, expr, podmesh)
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (PodFrontDoor, ServingLoop,
                                       ServingPolicy, ServingRequest,
                                       TenantPolicy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)
EASY_MS = 300_000.0

MIB = 1 << 20


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    faults.reset_clock()
    yield
    obs.disable()
    obs.reset()
    faults.reset_clock()


@pytest.fixture(scope="module")
def tenant_sets():
    rng = np.random.default_rng(0x90D)
    out = []
    for s in range(3):
        out.append(DeviceBitmapSet(
            [RoaringBitmap.from_values(np.unique(
                rng.integers(0, 1 << 16, 700).astype(np.uint32)))
             for _ in range(5)], layout="dense"))
    return out


@pytest.fixture(scope="module")
def reference(tenant_sets):
    return MultiSetBatchEngine(tenant_sets)


#: mixed-regime plan over 2 hosts: tenant 0 capacity-sharded (the
#: pod-spanning mesh), tenant 1 replicated on both (rendezvous winner:
#: host 1), tenant 2 local to host 0
MIXED_PLAN = podmesh.PlacementPlan(
    regimes=("sharded", "replicated-2", "local"),
    hosts=((0, 1), (0, 1), (0,)),
    bytes_per_host=(0, 0))


def _policy(**kw) -> ServingPolicy:
    kw.setdefault("guard", NOSLEEP)
    kw.setdefault("default_deadline_ms", EASY_MS)
    kw.setdefault("pool_target", 4)
    return ServingPolicy(**kw)


def _front_door(tenant_sets, plan=MIXED_PLAN, n_hosts=2, **kw):
    return PodFrontDoor(tenant_sets, pod=podmesh.PodMesh.simulate(n_hosts),
                        plan=plan, policy=_policy(), **kw)


def _requests(n, n_sets=3, seed=0xA12):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sid = int(rng.integers(n_sets))
        form = "bitmap" if i % 3 == 0 else "cardinality"
        if i % 7 == 3:
            q = expr.ExprQuery(
                expr.and_(expr.or_(0, 1), expr.not_(2)), form=form)
        else:
            op = ("or", "and", "xor", "andnot")[int(rng.integers(4))]
            q = BatchQuery(op, (0, 1, 2), form=form)
        out.append(ServingRequest(sid, q, tenant=f"t{sid}"))
    return out


def _assert_exact(reference, t):
    assert t.status == "done", (t.status, t.error)
    ref = reference._engines[t.pod_sid]._sequential_one(t.query)
    assert t.result.cardinality == ref.cardinality
    if t.query.form == "bitmap":
        assert t.result.bitmap == ref


# ------------------------------------------------------- placement planner

def test_plan_pod_placement_regimes():
    """The three-regime decision matrix: capacity tenants shard, hot
    small tenants replicate N-wide, the rest balance locally."""
    #          big        hot-small  cold      cold
    t_bytes = [100 * MIB, 4 * MIB,   8 * MIB,  8 * MIB]
    raw = insights.plan_pod_placement(
        t_bytes, 4, budget_per_host=64 * MIB,
        qps=[1.0, 12.0, 1.0, 1.0])
    assert raw["regimes"][0] == "sharded"
    assert raw["hosts"][0] == [0, 1, 2, 3]
    assert raw["regimes"][1].startswith("replicated-")
    n = int(raw["regimes"][1].split("-")[1])
    assert 2 <= n <= 4 and len(raw["hosts"][1]) == n
    assert raw["regimes"][2] == raw["regimes"][3] == "local"
    # locals land on distinct least-loaded hosts
    assert raw["hosts"][2] != raw["hosts"][3]
    assert not raw["over_budget"]


def test_plan_pod_placement_degenerate_and_budget():
    # single host: everything local, nothing to spread
    raw = insights.plan_pod_placement([MIB, 200 * MIB], 1,
                                      budget_per_host=64 * MIB)
    assert raw["regimes"] == ["local", "local"]
    # uniform traffic is never "hot": nothing replicates without skew
    raw = insights.plan_pod_placement([4 * MIB] * 3, 2,
                                      qps=[1.0, 1.0, 1.0])
    assert raw["regimes"] == ["local"] * 3
    # over-budget is reported, not hidden
    raw = insights.plan_pod_placement([30 * MIB] * 4, 2,
                                      budget_per_host=72 * MIB,
                                      qps=[8.0, 1.0, 1.0, 1.0])
    assert raw["regimes"][0].startswith("replicated")
    assert raw["over_budget"]


def test_place_resolves_from_footprint_model(tenant_sets):
    pod = podmesh.PodMesh.simulate(2)
    plan = podmesh.place(tenant_sets, pod)
    assert plan.n_tenants == 3
    assert all(r == "local" for r in plan.regimes)   # no rate data
    assert sum(plan.bytes_per_host) == sum(
        podmesh.tenant_bytes_of(tenant_sets))
    # rates flip the hot tenant to replicated-N
    plan2 = podmesh.place(tenant_sets, pod, qps=[50.0, 1.0, 1.0])
    assert plan2.regime(0).startswith("replicated-")
    assert len(plan2.hosts_of(0)) >= 2


def test_route_is_consistent_under_host_loss():
    """Rendezvous property: losing a host only moves the tenants that
    host was serving; every survivor keeps its route."""
    plan = podmesh.PlacementPlan(
        regimes=tuple(["local"] * 32),
        hosts=tuple((0, 1, 2, 3) for _ in range(32)),
        bytes_per_host=(0, 0, 0, 0))
    before = {s: podmesh.route(plan, s, (0, 1, 2, 3)) for s in range(32)}
    assert len(set(before.values())) > 1      # spread, not clumped
    after = {s: podmesh.route(plan, s, (0, 1, 3)) for s in range(32)}
    for s in range(32):
        if before[s] != 2:
            assert after[s] == before[s], f"tenant {s} moved needlessly"
        else:
            assert after[s] in (0, 1, 3)
    assert podmesh.route(plan, 0, ()) is None


# ------------------------------------------------------------ parity path

def test_pod_parity_bit_exact_matrix(tenant_sets, reference):
    """The acceptance matrix: (op x placement regime x flat/expression x
    bitmap/cardinality) through the routed pod front door, bit-exact vs
    the single-host engine — including the capacity tenant through the
    pod-spanning sharded mesh."""
    fd = _front_door(tenant_sets)
    tickets = [fd.submit(r) for r in _requests(28)]
    fd.drain()
    hosts = {t.pod_host for t in tickets}
    assert "capacity" in hosts and len(hosts) >= 3   # all regimes served
    for t in tickets:
        _assert_exact(reference, t)
    snap = fd.snapshot()
    assert snap["stats"]["routed"] == 28
    assert snap["backlog"] == 0
    assert set(snap["placement"]) == {"0", "1", "2"}


def test_misroute_forwarding(tenant_sets, reference):
    """A request arriving at the wrong host forwards to its routed host
    — counted, traced, served identically."""
    fd = _front_door(tenant_sets)
    before = fd.stats["forwarded"]
    # tenant 2 is local to host 0: arrival at host 1 must forward
    t = fd.submit(ServingRequest(2, BatchQuery("or", (0, 1)),
                                 tenant="t2"), via_host=1)
    assert t.pod_forwarded and t.pod_host == 0
    # arrival at the right host does not
    t2 = fd.submit(ServingRequest(2, BatchQuery("or", (0, 1)),
                                  tenant="t2"), via_host=0)
    assert not t2.pod_forwarded
    fd.drain()
    assert fd.stats["forwarded"] == before + 1
    _assert_exact(reference, t)
    _assert_exact(reference, t2)


# --------------------------------------------------------------- host loss

def test_host_drop_reroutes_to_replica(tenant_sets, reference):
    """The ``reroute`` rung under ROARING_TPU_FAULTS on the fault clock:
    an injected host loss marks the host down mid-stream and every
    affected ticket re-serves from a replica or single-host mode —
    typed events only, nothing silent, bit-exact results."""
    fd = _front_door(tenant_sets)
    tickets = [fd.submit(r) for r in _requests(16, seed=0xB0B)]
    # replicated tenant 1 routes to host 1, local tenant 2 to host 0
    assert {t.pod_host for t in tickets} == {0, 1, "capacity"}
    rerouted = [t for t in tickets if t.pod_host == 1]
    t0 = faults.clock()
    with faults.inject("coordinator@host1=1.0:9"):
        fd.pump()                      # host 1 drops here
        out = fd.drain()
    assert faults.clock() >= t0
    assert not fd.pod.is_alive(1) and fd.pod.is_alive(0)
    assert fd.stats["host_drops"] == 1
    assert fd.stats["reroutes"] == len(rerouted) > 0
    # nothing silent: every ticket completed or carries a typed error
    assert all(t.status == "done" or t.error is not None
               for t in tickets)
    for t in tickets:
        _assert_exact(reference, t)
    # the replicated tenant re-served from its host-0 replica
    assert all(t.pod_host == 0 for t in rerouted)
    assert all(t.pod_host in (0, "capacity") for t in tickets)
    assert len(out) >= fd.stats["reroutes"]


def test_host_drop_without_replica_demotes_to_single(tenant_sets,
                                                     reference):
    """A tenant whose ONLY placement host dies demotes to single-host
    mode (the authoritative pooled engine) instead of failing — and a
    submit AFTER the drop routes straight there."""
    plan = podmesh.PlacementPlan(
        regimes=("local", "local", "local"),
        hosts=((0,), (0,), (1,)), bytes_per_host=(0, 0))
    fd = _front_door(tenant_sets, plan=plan)
    queued = [fd.submit(ServingRequest(0, BatchQuery("xor", (0, 1, 2)),
                                       tenant="t0"))
              for _ in range(3)]
    fd.fail_host(0)
    late = fd.submit(ServingRequest(1, BatchQuery("and", (0, 1)),
                                    tenant="t1"))
    assert late.pod_host == "single"
    fd.drain()
    for t in queued + [late]:
        _assert_exact(reference, t)
    assert fd.stats["single_demotions"] >= 4
    assert fd.stats["host_drops"] == 1


def test_capacity_failure_demotes_tickets_to_single(tenant_sets,
                                                    reference):
    """A host-loss fault that escapes even the capacity engine's own
    mesh->single->sequential ladder walks the pod reroute rung into
    single-host mode rather than standing as a pool failure."""
    fd = _front_door(tenant_sets)
    t = fd.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                 tenant="t0"))
    # simulate the escaped failure the serving loop would hand back
    fd._cap_loop.evict_queued()
    t.status = "failed"
    t.error = errors.HostLost("pod: capacity dispatch lost its mesh")
    out = fd._after_pump("capacity", [t])
    assert out == []                   # consumed by the reroute rung
    fd.drain()
    _assert_exact(reference, t)
    assert t.pod_host == "single"


def test_reroute_fires_once_typed(tenant_sets):
    """The rung does not ping-pong: a ticket that already rerouted keeps
    its typed failure."""
    fd = _front_door(tenant_sets)
    t = fd.submit(ServingRequest(2, BatchQuery("or", (0, 1)),
                                 tenant="t2"))
    fd._loops[1].evict_queued()
    t.status = "failed"
    t.error = errors.HostLost("pod: host 1 lost")
    t.pod_rerouted = True              # second strike
    out = fd._after_pump(1, [t])
    assert out == [t] and t.status == "failed"
    assert isinstance(t.error, errors.CoordinatorTimeout)


# --------------------------------------------------------- fair share

def test_cross_host_fair_share_survives_reroute(tenant_sets):
    """Stride state is pod-global: after a host drop moves tenant b onto
    tenant a's host, the very first merged pool still splits slots by
    weight — b neither monopolizes (no vtime reset) nor starves."""
    plan = podmesh.PlacementPlan(
        regimes=("local", "local", "local"),
        hosts=((0,), (1, 0), (1,)), bytes_per_host=(0, 0))
    pol = _policy(pool_target=6, tenants={
        "t0": TenantPolicy(weight=2.0), "t1": TenantPolicy(weight=1.0)})
    fd = PodFrontDoor(tenant_sets, pod=podmesh.PodMesh.simulate(2),
                      plan=plan, policy=pol)
    for _ in range(12):
        fd.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                 tenant="t0"))
        fd.submit(ServingRequest(1, BatchQuery("or", (0, 1)),
                                 tenant="t1"))
    fd._gossip()
    fd.fail_host(1)                    # t1's queue adopts onto host 0
    picked = fd._loops[0]._pick(6)
    by: dict = {}
    for t in picked:
        by[t.request.tenant] = by.get(t.request.tenant, 0) + 1
    assert by == {"t0": 4, "t1": 2}, by


def test_gossip_merges_vtime_monotone(tenant_sets):
    fd = _front_door(tenant_sets)
    fd._loops[0]._vtime.update({"a": 5.0, "b": 1.0})
    fd._loops[1]._vtime.update({"a": 2.0, "c": 3.0})
    board = fd._gossip()
    assert board["a"] == 5.0 and board["b"] == 1.0 and board["c"] == 3.0
    assert fd._loops[1]._vtime["a"] == 5.0      # pushed up, never down
    assert fd._gossip()["a"] == 5.0             # idempotent


# ------------------------------------------------------- pump-on-timer

def test_pump_driver_serves_without_caller(tenant_sets, reference):
    """PR 10's named debt: the daemon pump thread makes the loop
    actually always-on — submit, wait, served."""
    loop = ServingLoop(MultiSetBatchEngine(tenant_sets),
                       _policy(pool_target=4))
    drv = loop.start_pump(interval_s=0.002)
    try:
        tickets = [loop.submit(ServingRequest(
            i % 3, BatchQuery("or", (0, 1)), tenant=f"t{i % 3}"))
            for i in range(8)]
        drv.kick()
        deadline = time.monotonic() + 60
        while (any(t.status == "queued" for t in tickets)
               and time.monotonic() < deadline):
            time.sleep(0.005)
    finally:
        drv.stop(drain=True)
    assert drv.last_error is None
    assert drv.ticks >= 1 and drv.completed >= 8
    for t in tickets:
        assert t.status == "done"
        ref = reference._engines[t.request.set_id]._sequential_one(
            t.query)
        assert t.result.cardinality == ref.cardinality
    assert not drv.running


def test_pump_driver_fault_clock_deadline(tenant_sets):
    """Fault-clock compatibility: advancing the virtual clock and
    kicking the driver sheds an expired request deterministically —
    no real waiting is involved in the expiry."""
    loop = ServingLoop(MultiSetBatchEngine(tenant_sets),
                       _policy(pool_target=64))   # never fills
    drv = loop.start_pump(interval_s=0.002)
    try:
        t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                       tenant="t0", deadline_ms=10.0))
        faults.advance_clock(0.5)       # virtual: the deadline passed
        drv.kick()
        deadline = time.monotonic() + 60
        while t.status == "queued" and time.monotonic() < deadline:
            drv.kick()
            time.sleep(0.002)
    finally:
        drv.stop()
    assert t.status == "shed" and t.error.reason == "expired"


def test_pod_front_door_pump_driver(tenant_sets, reference):
    """The always-on driver over the whole routed pod: each regime's
    loop fills its pool target and the daemon thread dispatches it with
    no caller involvement."""
    fd = _front_door(tenant_sets)
    drv = fd.start_pump(interval_s=0.002)
    try:
        # 8 requests per tenant: every per-host loop (and the capacity
        # loop) fills the pool target of 4 at least twice
        tickets = [fd.submit(ServingRequest(
            sid, BatchQuery(("or", "and", "xor", "andnot")[i % 4],
                            (0, 1, 2)), tenant=f"t{sid}"))
            for sid in range(3) for i in range(8)]
        drv.kick()
        deadline = time.monotonic() + 120
        while (any(t.status == "queued" for t in tickets)
               and time.monotonic() < deadline):
            time.sleep(0.005)
    finally:
        drv.stop(drain=True)
    assert drv.last_error is None
    for t in tickets:
        _assert_exact(reference, t)


def test_rebalance_replans_and_requeues_without_demotion(tenant_sets,
                                                        reference):
    """``rebalance`` re-plans from observed/given rates and rebuilds the
    host loops; queued tickets re-route through the FRESH plan — onto a
    (possibly identical) alive host, never spuriously into single-host
    mode."""
    plan = podmesh.PlacementPlan(
        regimes=("local", "local", "local"),
        hosts=((0,), (0,), (1,)), bytes_per_host=(0, 0))
    fd = _front_door(tenant_sets, plan=plan)
    tickets = [fd.submit(ServingRequest(
        sid, BatchQuery("or", (0, 1)), tenant=f"t{sid}"))
        for sid in (0, 1, 2, 0)]
    rep = fd.rebalance(qps=[50.0, 1.0, 1.0])
    assert rep["changed"]
    assert fd.plan.regime(0).startswith("replicated-")
    fd.drain()
    for t in tickets:
        _assert_exact(reference, t)
    # every requeued ticket landed on a real host loop
    assert fd.stats["single_demotions"] == 0
    assert all(t.pod_host in (0, 1) for t in tickets)
    assert fd.stats["reroutes"] == len(tickets)


def test_warmup_runs_per_host(tenant_sets):
    """``warmup`` pre-compiles every host's own vocabulary (plus the
    capacity engine's), so a routed steady state still compiles
    nothing on any host."""
    fd = _front_door(tenant_sets)
    reports = fd.warmup(rungs=(2,))
    assert set(reports) == {"0", "1", "capacity"}
    for rep in reports.values():
        assert "wall_ms" in rep


# ------------------------------------------------- maintenance worker

def _fresh_set(seed=0x3A5, n=3, size=500):
    rng = np.random.default_rng(seed)
    return DeviceBitmapSet(
        [RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 15, size).astype(np.uint32)))
         for _ in range(n)], layout="dense")


def test_maintenance_defers_escalated_repack():
    """PR 12's named debt: a structural delta with a worker attached
    returns immediately (mode="repack_queued"), the pre-delta image
    keeps serving bit-exactly, and drain() commits the repack with the
    version/structure bump + cache invalidation."""
    from roaringbitmap_tpu.mutation import MaintenanceWorker

    ds = _fresh_set()
    eng = MultiSetBatchEngine([ds])
    q = BatchQuery("or", (0, 1, 2))
    before = eng._engines[0]._sequential_one(q).cardinality
    w = MaintenanceWorker()
    try:
        new_vals = np.array([0x7F010001, 0x7F020002], np.uint32)
        rep = ds.apply_delta(adds={0: new_vals}, worker=w)
        assert rep["mode"] == "repack_queued"
        assert rep["repack_reason"] == "structural"
        # deferred commit: pre-delta image serves, version unmoved
        assert ds.version == 0
        got = eng.execute([(0, [q])])[0][0].cardinality
        assert got == before
        w.drain()
        assert ds.version == 1 and ds.structure_version == 1
        hosts = ds.host_bitmaps()
        assert all(int(v) in hosts[0] for v in new_vals)
        got = eng.execute([(0, [q])])[0][0].cardinality
        assert got == eng._engines[0]._sequential_one(q).cardinality
        assert got == before + 2
        assert w.jobs_done == 1 and w.jobs_failed == 0
    finally:
        w.stop()


def test_maintenance_interleaved_patch_survives_commit():
    """A value patch landing between queue and commit is never lost:
    the commit recomputes the post-delta sources from the then-current
    state."""
    from roaringbitmap_tpu.mutation import MaintenanceWorker

    ds = _fresh_set(seed=0x3A6)
    w = MaintenanceWorker(start=False)    # inline drain: deterministic
    ds.apply_delta(adds={0: np.array([0x7F030001], np.uint32)}, worker=w)
    # in-place patch while the repack is queued (existing container)
    patched = int(ds.host_bitmaps()[1].to_array()[0])
    ds.apply_delta(removes={1: np.array([patched], np.uint32)},
                   worker=w)
    w.drain()
    hosts = ds.host_bitmaps()
    assert 0x7F030001 in hosts[0]
    assert patched not in hosts[1]
    w.stop()


def test_maintenance_coalesces_escalation_bursts():
    """A burst of escalating deltas pays ONE repack: only the first
    queues a commit job, the rest ride its pending list — and every
    delta's values land."""
    from roaringbitmap_tpu.mutation import MaintenanceWorker

    ds = _fresh_set(seed=0x3A7)
    w = MaintenanceWorker(start=False)    # inline drain: deterministic
    vals = [0x7F040001, 0x7F050002, 0x7F060003]
    for i, v in enumerate(vals):
        rep = ds.apply_delta(adds={i: np.array([v], np.uint32)},
                             worker=w)
        assert rep["mode"] == "repack_queued"
    w.drain()
    assert w.jobs_done == 1               # one combined commit
    assert ds.version == 1 and ds.structure_version == 1
    hosts = ds.host_bitmaps()
    for i, v in enumerate(vals):
        assert v in hosts[i]
    w.stop()


def test_double_host_loss_lands_in_single_not_stranded(tenant_sets,
                                                       reference):
    """A ticket rerouted once whose NEW host also dies goes to the
    terminal single-host loop — never stranded queued, never silent."""
    plan = podmesh.PlacementPlan(
        regimes=("replicated-2", "local", "local"),
        hosts=((0, 1), (0,), (1,)), bytes_per_host=(0, 0))
    fd = _front_door(tenant_sets, plan=plan)
    t = fd.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                 tenant="t0"))
    first = t.pod_host
    fd.fail_host(first)                   # hop 1: the replica
    assert t.status == "queued" and t.pod_host == 1 - first
    fd.fail_host(1 - first)               # hop 2: terminal single
    assert t.pod_host == "single"
    fd.drain()
    _assert_exact(reference, t)


def test_maintenance_failed_job_is_visible_not_fatal():
    from roaringbitmap_tpu.mutation import MaintenanceWorker

    w = MaintenanceWorker()
    try:
        w.submit(lambda: 1 / 0, kind="repack", desc="doomed")
        w.drain()
        assert w.jobs_failed == 1
        assert isinstance(w.last_error, ZeroDivisionError)
        done = []
        w.submit(lambda: done.append(1))
        w.drain()
        assert done == [1]              # the queue keeps moving
    finally:
        w.stop()


# ------------------------------------------- multihost probe satellite

def test_probe_latency_surfaces_in_obs_snapshot():
    """The pre-flight TCP probe's latency + coordinator identity land in
    obs.snapshot()["multihost"] — a slow coordinator is visible before
    it times out."""
    from roaringbitmap_tpu.parallel import multihost

    srv = socket.socket()
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        multihost._STATE.clear()
        multihost._STATE.update(coordinator=f"127.0.0.1:{port}",
                                process_id=1, timeout_s=5.0,
                                probe_ms=None, status="probing")
        multihost._probe_coordinator(
            f"127.0.0.1:{port}", 5.0, time.monotonic() + 5.0,
            lambda: "probe-test", errors)
    finally:
        srv.close()
    snap = obs.snapshot()
    assert "multihost" in snap
    info = snap["multihost"]
    assert info["coordinator"].endswith(str(port))
    assert isinstance(info["probe_ms"], float) and info["probe_ms"] >= 0
    assert info["process_id"] == 1
    gauges = snap.get("gauges", {})
    assert any("rb_multihost_probe_seconds" in str(k) for k in gauges)


def test_failed_bootstrap_records_typed_state():
    from roaringbitmap_tpu.parallel import multihost

    with faults.inject("coordinator@multihost=1.0:11"):
        with pytest.raises(errors.CoordinatorTimeout):
            multihost.initialize("10.9.9.9:1", num_processes=2,
                                 process_id=0, timeout=3)
    info = obs.snapshot()["multihost"]
    assert info["status"] == "failed"
    assert info["coordinator"] == "10.9.9.9:1"


# ------------------------------------------- 2-process cluster harness

_POD_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
sys.path.insert(0, {repo!r})
pid, port = int(sys.argv[1]), sys.argv[2]
from roaringbitmap_tpu.parallel import multihost
multihost.initialize(f"127.0.0.1:{{port}}", num_processes=2,
                     process_id=pid)
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                        MultiSetBatchEngine, podmesh)
from roaringbitmap_tpu.runtime import guard
from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                       ServingRequest)

assert jax.process_count() == 2
# the probe satellite: bootstrap state rides obs.snapshot(), and the
# non-coordinator rank records its pre-flight probe latency
mh = obs.snapshot()["multihost"]
assert mh["status"] == "initialized", mh
assert mh["process_count"] == 2, mh
if pid == 1:
    assert isinstance(mh["probe_ms"], float), mh

pod = podmesh.PodMesh.detect()
assert pod.n_hosts == 2, pod.snapshot()
assert pod.hosts[pid].local and not pod.hosts[1 - pid].local
assert pod.local_host == pid
assert not podmesh.supports_pod_dispatch()   # CPU pod: no collectives

# each host feeds ONLY its addressable shard of a globally-placed array
mesh = pod.pod_mesh()
img = np.arange(2 * 8, dtype=np.uint32).reshape(2, 8)
arr = podmesh.global_put(img, NamedSharding(mesh, P("rows", None)))
shards = arr.addressable_shards
assert len(shards) == 1, shards
assert shards[0].data.shape == (1, 8), shards[0].data.shape
assert (np.asarray(shards[0].data) == img[shards[0].index]).all()

# identical tenant universe on both hosts (same seed): the placement
# plan and every route agree across processes with zero coordination
rng = np.random.default_rng(3)
sets = [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
    rng.integers(0, 1 << 16, 400).astype(np.uint32)))
    for _ in range(4)], layout="dense") for _ in range(4)]
plan = podmesh.place(sets, pod)
routes = [podmesh.route(plan, s, pod.alive()) for s in range(4)]
print("POD2_PLAN", pid, list(plan.regimes), [list(h) for h in plan.hosts],
      routes)

# per-host front door: this process serves exactly its routed share
fd = PodFrontDoor(sets, pod=pod, plan=plan, policy=ServingPolicy(
    pool_target=4, default_deadline_ms=600000.0,
    guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)))
ref = MultiSetBatchEngine(sets)
served = 0
for i in range(16):
    sid = i % 4
    if fd.owner_host(sid) not in fd._loops:
        continue
    t = fd.submit(ServingRequest(
        sid, BatchQuery(("or", "and", "xor", "andnot")[i % 4], (0, 1)),
        tenant=f"t{{sid}}"))
    fd.drain()
    r = ref._engines[sid]._sequential_one(t.request.query)
    assert t.status == "done" and t.result.cardinality == r.cardinality
    served += 1
assert served > 0
fd._gossip()          # the KV gossip path must never throw
print("POD2_OK", pid, served)
""".format(repo=REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_pod_bringup(tmp_path):
    """The real 2-process cluster (tests/test_multihost.py extended):
    bootstrap + probe snapshot, PodMesh.detect host ownership,
    addressable-shard feeding, cross-process placement/routing
    agreement, and per-host routed serving parity."""
    worker = tmp_path / "pod_worker.py"
    worker.write_text(_POD_WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "ROARING_TPU_FAULTS")}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"POD2_OK {i}" in out
    # the plan + route lines must agree verbatim across processes
    plans = [[ln.split(" ", 2)[2] for ln in out.splitlines()
              if ln.startswith("POD2_PLAN")][0] for out in outs]
    assert plans[0] == plans[1], plans
