"""Cross-tenant multi-set batch engine acceptance (ISSUE 5).

Pins:
- pooled execution bit-exact, query by query, against per-set sequential
  ``BatchEngine`` loops across (op x layout x engine rung) — including
  under injected oom/transient faults (pool splitting stays bit-exact);
- the S=1 fast path: a pool referencing one set routes through that
  set's ``BatchEngine.execute`` with zero pooled planning and zero new
  device buffers (HBM-ledger regression);
- proactive pool splitting respects ``ROARING_TPU_HBM_BUDGET``: splits
  fire BEFORE dispatch, every dispatched launch's prediction fits the
  budget (asserted from the ``multiset.memory`` trace events), counted
  under ``rb_multiset_*``;
- the ``multiset.*`` span vocabulary and pooled predicted-vs-measured
  memory accounting;
- CPU-proxy performance acceptance (slow lane): pooled Q=64 over S=8
  sets >= 3x the per-set sequential loop's QPS, and the pipelined
  dispatcher hides >= 50% of host plan+pack wall time at Q=64 (overlap
  ratio read back from the ``multiset.pipeline`` span).
"""

import json

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.parallel import (BatchEngine, BatchGroup, BatchQuery,
                                        DeviceBitmapSet, MultiSetBatchEngine)
from roaringbitmap_tpu.parallel.multiset import random_multiset_pool
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.runtime import lattice as rt_lattice

S_SIZES = (8, 6, 8)     # bitmaps per tenant set


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture(scope="module")
def tenant_bitmaps():
    """Three tenants with different shapes: sparse uniform, a shared
    dense chunk (bitmap containers), and a run-heavy set."""
    rng = np.random.default_rng(0x7E4A)
    out = []
    for s, n in enumerate(S_SIZES):
        bms = []
        for i in range(n):
            vals = [rng.integers(0, 1 << 17, 2000).astype(np.uint32)]
            if s == 1 and i % 2 == 0:
                vals.append(np.arange(1 << 16, (1 << 16) + 9000,
                                      dtype=np.uint32))
            if s == 2:
                start = int(rng.integers(0, 1 << 16))
                vals.append(np.arange(start, start + 1500,
                                      dtype=np.uint32))
            bms.append(RoaringBitmap.from_values(
                np.unique(np.concatenate(vals))))
        out.append(bms)
    return out


@pytest.fixture(scope="module")
def pool():
    return random_multiset_pool(list(S_SIZES), 18, seed=0xBEEF)


def _per_set_reference(tenant_bitmaps, pool, engine="xla"):
    """The per-set sequential BatchEngine loop the pooled engine must
    match bit-exactly — one execute per tenant."""
    out = []
    for g in pool:
        be = BatchEngine.from_bitmaps(tenant_bitmaps[g.set_id],
                                      layout="dense")
        out.append(be.execute(list(g.queries), engine=engine))
    return out


def _assert_bit_exact(got, want, tag):
    for gi, (grows, wrows) in enumerate(zip(got, want)):
        assert len(grows) == len(wrows)
        for qi, (a, b) in enumerate(zip(grows, wrows)):
            assert a.cardinality == b.cardinality, (tag, gi, qi)
            if b.bitmap is not None:
                assert a.bitmap == b.bitmap, (tag, gi, qi)


@pytest.fixture(scope="module")
def oracle(tenant_bitmaps, pool):
    bm_pool = [BatchGroup(g.set_id, [
        BatchQuery(q.op, q.operands, form="bitmap") for q in g.queries])
        for g in pool]
    return bm_pool, _per_set_reference(tenant_bitmaps, bm_pool)


@pytest.mark.parametrize("layout,engines", [
    ("dense", ("xla", "xla-vmap", "pallas")),
    ("compact", ("xla", "pallas")),
    ("counts", ("xla",)),
])
def test_pooled_matches_per_set_loops(tenant_bitmaps, oracle, layout,
                                      engines):
    """The (op x layout x engine) parity matrix: a mixed-op pool over
    every tenant, materialized bitmaps, bit-exact against the per-set
    sequential loop on every rung."""
    bm_pool, want = oracle
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps,
                                               layout=layout)
    for e in engines:
        got = eng.execute(bm_pool, engine=e)
        _assert_bit_exact(got, want, (layout, e))


def test_pool_splitting_bit_exact_under_faults(tenant_bitmaps, oracle):
    """oom/transient injection: reactive pool halvings and retries fire
    and the pooled results stay bit-exact (the CI fault lane re-runs the
    whole module under a global schedule on top of this)."""
    bm_pool, want = oracle
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    with faults.inject("oom=0.4,transient=0.1:0xAB"):
        got = eng.execute(bm_pool, engine="xla")
    _assert_bit_exact(got, want, "faults")
    with faults.inject("lowering=1.0:0xAC"):     # every device rung dead
        got = eng.execute(bm_pool, engine="xla")
    _assert_bit_exact(got, want, "sequential-floor")


def test_jit_vs_eager_and_raw(tenant_bitmaps, oracle):
    bm_pool, want = oracle
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    _assert_bit_exact(eng.execute(bm_pool, engine="xla", jit=False),
                      want, "eager")
    _assert_bit_exact(eng.execute(bm_pool, engine="xla", fallback=False),
                      want, "raw")


def test_s1_pool_routes_through_single_set_path(tenant_bitmaps):
    """Satellite: a pool referencing ONE set must ride the existing
    single-set path — no pooled plan/program, no new device buffers
    (the HBM ledger is the witness: only resident-set construction
    registers bytes, so the snapshot must not move)."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    queries = [BatchQuery("or", (0, 1, 2)), BatchQuery("xor", (1, 3))]
    ledger_before = obs_memory.LEDGER.snapshot()
    got = eng.execute([BatchGroup(1, queries)], engine="xla")
    assert obs_memory.LEDGER.snapshot() == ledger_before
    # zero pooled machinery engaged
    assert len(eng._plans) == 0 and len(eng._programs) == 0
    # and the single-set engine's own caches served the call
    be = eng._engines[1]
    # plan keys carry the set's mutation version (docs/MUTATION.md),
    # the attached-column token (docs/ANALYTICS.md; () while bare),
    # plus the lattice token (docs/LATTICE.md; None while inactive)
    assert (tuple(queries), be._ds.version, be._columns_token(),
            rt_lattice.plan_token()) in be._plans
    want = be.execute(queries, engine="xla")
    assert [r.cardinality for r in got[0]] == \
        [r.cardinality for r in want]


def test_budget_pool_split_proactive_and_bit_exact(tenant_bitmaps, oracle,
                                                   tmp_path):
    """ROARING_TPU_HBM_BUDGET respected per-pool: the pool halves BEFORE
    dispatch, every dispatched launch's prediction fits the budget
    (multiset.memory events), results stay bit-exact, and the splits are
    counted under rb_multiset_*."""
    bm_pool, want = oracle
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    full = eng.predict_dispatch_bytes(bm_pool)
    assert full > 0
    budget = max(1, full // 3)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    policy = guard.GuardPolicy(hbm_budget=budget)
    got = eng.execute(bm_pool, engine="xla", policy=policy)
    obs.disable()
    _assert_bit_exact(got, want, "budget")
    assert eng.proactive_split_count > 0

    spans = [json.loads(line) for line in open(path)]
    mems = [ev for s in spans if s["name"] == "multiset.dispatch"
            for ev in s["events"] if ev["name"] == "multiset.memory"]
    assert mems and all(ev["predicted_bytes"] <= budget for ev in mems)
    splits = [ev for s in spans for ev in s["events"]
              if ev["name"] == "proactive_split"
              and ev.get("site") == "multiset"]
    assert len(splits) == eng.proactive_split_count
    assert all(ev["predicted_bytes"] > ev["budget_bytes"]
               for ev in splits)
    pipes = [s for s in spans if s["name"] == "multiset.pipeline"]
    assert pipes and pipes[0]["tags"]["launches"] > 1
    snap = obs.snapshot()
    pro = snap["counters"]["rb_multiset_proactive_splits_total"]
    assert pro[0]["value"] == eng.proactive_split_count


def test_memory_event_and_pool_metrics(tenant_bitmaps, pool, tmp_path):
    """Pooled dispatches report predicted-vs-measured HBM (the
    batch.memory-equivalent multiset.memory event) and the pool gauges
    move."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    eng.execute(pool, engine="xla")
    obs.disable()
    mem = eng.last_dispatch_memory
    assert mem["predicted_bytes"] > 0 and mem["sets"] == len(S_SIZES)
    assert mem["measured_peak_bytes"] > 0      # AOT-compiled accounting
    spans = [json.loads(line) for line in open(path)]
    names = {s["name"] for s in spans}
    assert {"multiset.execute", "multiset.plan", "multiset.pool",
            "multiset.dispatch", "multiset.readback",
            "multiset.pipeline"} <= names
    snap = obs.snapshot()
    occ = snap["gauges"]["rb_multiset_pool_occupancy"][0]["value"]
    assert 0.0 < occ <= 1.0
    assert snap["counters"]["rb_multiset_queries_total"][0]["value"] \
        == sum(len(g.queries) for g in pool)
    # one pooled launch served 3 tenants: 2 launches saved
    saved = snap["counters"]["rb_multiset_launches_saved_total"]
    assert saved[0]["value"] == len(S_SIZES) - 1
    cell = obs_memory.dispatch_memory_cell(mem)
    assert cell["sets"] == len(S_SIZES) and cell["predicted_mb"] > 0


def test_execute_pipelined_streams_pools(tenant_bitmaps):
    """The serving-tick shape: several pools through one pipeline
    window, per-pool results bit-exact and order-preserved."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    pools = [random_multiset_pool(list(S_SIZES), 9, seed=s)
             for s in (21, 22, 23)]
    obs.reset()
    got = eng.execute_pipelined(pools, engine="xla")
    for p, rows in zip(pools, got):
        _assert_bit_exact(rows, _per_set_reference(tenant_bitmaps, p),
                          "pipelined")
    assert eng.last_pipeline["launches"] == len(pools)
    # launches-saved baseline is one-launch-per-referenced-set PER POOL:
    # a stream over the same tenants still amortizes every tick
    baseline = sum(len({g.set_id for g in p if g.queries}) for p in pools)
    saved = obs.snapshot()["counters"]["rb_multiset_launches_saved_total"]
    assert saved[0]["value"] == baseline - len(pools)


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_pipeline_depth_n_bit_exact_under_drain_faults(tenant_bitmaps,
                                                       depth):
    """Depth-N generalization (ISSUE 10): the pipelined dispatcher is
    bit-exact at N in {1, 2, 4} — including when faults surface only at
    DRAIN time (the ``multiset.drain`` injection scope), which re-runs
    that launch synchronously down the guarded ladder at any depth."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    pools = [random_multiset_pool(list(S_SIZES), 9, seed=s)
             for s in range(41, 47)]
    policy = guard.GuardPolicy(pipeline_depth=depth, backoff_base=0.0,
                               sleep=lambda s: None)
    with faults.inject("transient@multiset.drain=0.5:0xD4"):
        got = eng.execute_pipelined(pools, engine="xla", policy=policy)
    for p, rows in zip(pools, got):
        _assert_bit_exact(rows, _per_set_reference(tenant_bitmaps, p),
                          f"depth{depth}")
    assert eng.last_pipeline["depth"] == depth
    assert eng.last_pipeline["launches"] == len(pools)
    retries = obs.snapshot()["counters"].get(
        "rb_multiset_drain_retries_total", [])
    assert sum(r["value"] for r in retries) > 0, \
        "the drain-fault schedule never fired"


def test_pipeline_depth_env_knob(tenant_bitmaps, monkeypatch):
    monkeypatch.setenv(guard.ENV_PIPELINE_DEPTH, "4")
    assert guard.GuardPolicy.from_env().pipeline_depth == 4
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    pools = [random_multiset_pool(list(S_SIZES), 6, seed=s)
             for s in (51, 52)]
    got = eng.execute_pipelined(pools, engine="xla")
    for p, rows in zip(pools, got):
        _assert_bit_exact(rows, _per_set_reference(tenant_bitmaps, p),
                          "env-depth")
    assert eng.last_pipeline["depth"] == 4


def test_predict_dispatch_seconds_positive_and_monotone(tenant_bitmaps):
    """The serving loop's pre-dispatch time estimate: positive, and a
    bigger pool never predicts cheaper than a sub-pool of itself."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    pool = random_multiset_pool(list(S_SIZES), 16, seed=0xE57)
    pooled = eng._flatten(pool)[0]
    small = eng.predict_dispatch_seconds(pooled[:4])
    big = eng.predict_dispatch_seconds(pooled)
    assert 0 < small <= big
    assert eng.predict_dispatch_seconds([]) == 0.0


def test_shadow_check_catches_silent_corruption(tenant_bitmaps, pool):
    from roaringbitmap_tpu.runtime import errors

    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    policy = guard.GuardPolicy(shadow_rate=1.0)
    # clean run passes the full-rate shadow
    eng.execute(pool, engine="xla", policy=policy)
    with faults.inject("silent@multiset=1.0:3"):
        with pytest.raises(errors.ShadowMismatch):
            eng.execute(pool, engine="xla", policy=policy)


def test_group_validation(tenant_bitmaps):
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    with pytest.raises(IndexError):
        eng.execute([BatchGroup(9, [BatchQuery("or", (0, 1))])])
    assert eng.execute([]) == []
    assert eng.execute([BatchGroup(0, [])]) == [[]]
    with pytest.raises(ValueError):
        MultiSetBatchEngine([])


def test_pool_program_cache_bounds_recompiles(tenant_bitmaps):
    """Same pooled bucket signatures must reuse the compiled program."""
    eng = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps)
    p1 = [BatchGroup(0, [BatchQuery("or", (0, 1))]),
          BatchGroup(1, [BatchQuery("or", (2, 3))])]
    eng.execute(p1, engine="xla")
    n1 = len(eng._programs)
    p2 = [BatchGroup(0, [BatchQuery("or", (4, 5))]),
          BatchGroup(1, [BatchQuery("or", (0, 5))])]
    eng.execute(p2, engine="xla")
    assert len(eng._programs) == n1      # same signature -> cache hit


# ------------------------------------------------ adaptive layout default

def _uscensus_shaped(n: int = 10):
    """Mostly-singleton containers across many keys: ~1 value per 2^16
    segment, so the dense image inflates the serialized bytes by far
    more than 100x (the uscensus2000 shape, docs/USCENSUS2000_CLIFF.md)."""
    rng = np.random.default_rng(7)
    return [RoaringBitmap.from_values(np.unique(
        (rng.choice(400, size=20, replace=False).astype(np.uint32) << 16)
        + rng.integers(0, 1 << 16, 20).astype(np.uint32)))
        for _ in range(n)]


def test_choose_layout_flips_only_the_inflation_shape():
    from roaringbitmap_tpu.insights import analysis as insights

    rep = insights.choose_layout(_uscensus_shaped())
    assert rep["layout"] == "counts"
    assert rep["median_segment"] <= insights.AUTO_COUNTS_MEDIAN_SEGMENT
    assert rep["inflation_x"] > insights.AUTO_COUNTS_INFLATION_X
    # a dense-friendly shape (many values per segment) keeps the default
    rng = np.random.default_rng(8)
    normal = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 17, 3000).astype(np.uint32))
        for _ in range(6)]
    assert insights.choose_layout(normal)["layout"] == "dense"
    assert insights.choose_layout([])["layout"] == "dense"


def test_auto_layout_default_and_explicit_override():
    """DeviceBitmapSet's default is now layout="auto": the inflation
    shape builds counts-resident, an explicit layout= keeps the old
    behavior verbatim, and auto stays bit-exact with the dense build."""
    from roaringbitmap_tpu.parallel import aggregation

    bms = _uscensus_shaped()
    ds_auto = DeviceBitmapSet(bms)
    assert ds_auto.layout == "counts"
    ds_dense = DeviceBitmapSet(bms, layout="dense")
    assert ds_dense.layout == "dense" and ds_dense.words is not None
    # parity: the auto (counts) build answers every wide op exactly as
    # the explicit dense build does
    for op in ("or", "xor", "and"):
        assert ds_auto.aggregate(op) == ds_dense.aggregate(op), op
    want = aggregation.or_(*bms)
    assert ds_auto.aggregate("or") == want


# ---------------------------------------------------- CPU-proxy acceptance

def _tiny_tenants(s: int, n: int = 8):
    """Dispatch-floor-dominated tenants (the regime pooling exists for):
    tiny bitmaps make per-launch overhead, not per-query work, the
    cost."""
    rng = np.random.default_rng(s)
    return [RoaringBitmap.from_values(
        rng.integers(0, 1 << 16, 400).astype(np.uint32))
        for _ in range(n)]


def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


@pytest.mark.slow
def test_pooled_3x_vs_per_set_loop():
    """Acceptance: Q=64 spread over S=8 sets pooled into one launch runs
    >= 3x the QPS of the per-set sequential BatchEngine loop (8
    launches), bit-exact on every result."""
    s = 8
    tenants = [_tiny_tenants(40 + i) for i in range(s)]
    engines = [BatchEngine.from_bitmaps(t, layout="dense")
               for t in tenants]
    eng = MultiSetBatchEngine(engines)
    pool = random_multiset_pool([8] * s, 64, seed=0xACE, max_operands=3)
    assert sum(len(g.queries) for g in pool) == 64

    def per_set_loop():
        return [engines[g.set_id].execute(list(g.queries), engine="xla")
                for g in pool]

    want = per_set_loop()
    got = eng.execute(pool, engine="xla")
    _assert_bit_exact(got, want, "3x-parity")

    t_pool = min(_timed(lambda: eng.execute(pool, engine="xla"))
                 for _ in range(5))
    t_loop = min(_timed(per_set_loop) for _ in range(5))
    assert t_loop >= 3.0 * t_pool, (t_loop, t_pool, t_loop / t_pool)


@pytest.mark.slow
def test_pipeline_hides_half_the_host_time(tmp_path):
    """Acceptance: at Q=64 forced into multiple launches, the pipelined
    dispatcher hides >= 50% of host plan+pack wall time (overlap ratio
    from the multiset.pipeline span timings)."""
    s = 4
    tenants = [_tiny_tenants(60 + i) for i in range(s)]
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    # warm the compiled programs with same-shaped pools so the measured
    # pipeline pays planning/packing, not one-time compiles
    warm = [random_multiset_pool([8] * s, 16, seed=100 + i,
                                 max_operands=3) for i in range(4)]
    eng.execute_pipelined(warm, engine="xla")
    pools = [random_multiset_pool([8] * s, 16, seed=200 + i,
                                  max_operands=3) for i in range(4)]
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    eng.execute_pipelined(pools, engine="xla")
    obs.disable()
    spans = [json.loads(line) for line in open(path)]
    pipes = [s_ for s_ in spans if s_["name"] == "multiset.pipeline"]
    assert pipes
    tags = pipes[-1]["tags"]
    assert tags["launches"] == 4
    assert tags["host_ms"] > 0
    assert tags["overlap_ratio"] >= 0.5, tags
    assert eng.last_pipeline["overlap_ratio"] == tags["overlap_ratio"]


@pytest.mark.slow
def test_depth4_hides_at_least_the_depth2_overlap():
    """Acceptance (ISSUE 10): the depth-4 window hides >= the depth-2
    baseline's host-overlap ratio (best-of-3 each; a deeper window has
    strictly more launches to hide behind, so a materially WORSE ratio
    would mean the generalization broke the overlap accounting)."""
    s = 4
    tenants = [_tiny_tenants(80 + i) for i in range(s)]
    eng = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    pools = [random_multiset_pool([8] * s, 16, seed=300 + i,
                                  max_operands=3) for i in range(6)]
    eng.execute_pipelined(pools, engine="xla")      # warm compiles

    def ratio(depth: int) -> float:
        pol = guard.GuardPolicy(pipeline_depth=depth)
        best = 0.0
        for _ in range(3):
            eng.execute_pipelined(pools, engine="xla", policy=pol)
            best = max(best, eng.last_pipeline["overlap_ratio"])
        return best

    r2, r4 = ratio(2), ratio(4)
    assert r2 >= 0.5, r2
    assert r4 >= r2 * 0.9, (r2, r4)
