"""Cost & time observability (ISSUE 6) acceptance + contracts.

- ``Compiled.cost_analysis()`` captured at program build for every
  engine rung (pallas / xla / xla-vmap — pallas runs interpreted on the
  CPU proxy) and for the pooled multiset engine;
- roofline fraction in (0, 1] on a CPU-proxy Q=64 batch, and
  ``obs.snapshot()["cost"]`` populated per (site, engine) after a batch
  execute and a 3-tenant pooled execute;
- snapshot/reset symmetry + Prometheus render for the new families;
- ``BatchEngine.explain()`` reports per-bucket estimated device time
  from the same roofline model;
- SLO accounting: per-phase breakdown sums to within 5% of the query's
  wall, attained/missed counters (incl. under an injected
  ``ROARING_TPU_FAULTS`` slowdown) reconcile with the guard's dispatch
  stats, and a missed query's trace carries the phase-attributed
  ``slo`` event;
- compile-time export: ``rb_compile_seconds{site,cache}`` hit/miss and
  ``rb_first_query_seconds``;
- tools: bench_diff added/removed lanes, bench_sentry trajectories
  (clean / 20% step / monotone drift / removed lane).
"""

import importlib.util
import json
import os

import pytest

from roaringbitmap_tpu import obs
from roaringbitmap_tpu.obs import cost as obs_cost
from roaringbitmap_tpu.obs import slo as obs_slo
from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                     random_query_pool)
from roaringbitmap_tpu.parallel.multiset import (MultiSetBatchEngine,
                                                 random_multiset_pool)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.utils import datasets

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    obs_slo.set_attribution(False)
    yield
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    obs_slo.set_attribution(False)


@pytest.fixture(scope="module")
def engine():
    bms = datasets.synthetic_bitmaps(16, seed=21, universe=1 << 18,
                                     density=0.01)
    return BatchEngine.from_bitmaps(bms)


@pytest.fixture(scope="module")
def pool():
    return random_query_pool(16, 64)


def _read_trace(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# --------------------------------------------------------- cost capture

@pytest.mark.parametrize("eng_name", ["pallas", "xla", "xla-vmap"])
def test_cost_analysis_captured_per_engine(engine, pool, eng_name):
    """Every engine rung's AOT program carries cost_analysis, and its
    dispatch records achieved rates + a clamped roofline fraction."""
    engine.execute(pool[:8], engine=eng_name, fallback=False)
    cost = engine.last_dispatch_cost
    assert cost is not None and cost["device_ms"] >= 0
    assert cost["flops"] >= 0 and cost["bytes_accessed"] > 0
    assert 0.0 < cost["roofline_fraction"] <= 1.0
    assert cost["achieved_bytes_per_s"] > 0


def test_roofline_fraction_q64_and_snapshot_cost_section(engine, pool):
    """Acceptance: after a Q=64 batch and a 3-tenant pooled execute on
    the CPU proxy, obs.snapshot()["cost"] carries per-(site, engine)
    flops / bytes / roofline-fraction rows."""
    engine.execute(pool)                       # Q=64, auto -> xla on CPU
    tenants = [datasets.synthetic_bitmaps(8, seed=50 + i,
                                          universe=1 << 16, density=0.01)
               for i in range(3)]
    ms = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    ms.execute(random_multiset_pool([8] * 3, 24, seed=7))
    snap = obs.snapshot()["cost"]
    assert snap["peaks"]["peak_bytes_per_s"] > 0
    for site in ("batch_engine", "multiset"):
        assert site in snap["sites"], snap["sites"].keys()
        rows = snap["sites"][site]
        assert rows, site
        for row in rows.values():
            assert row["dispatches"] >= 1
            assert row["bytes_total"] > 0 and row["flops_total"] >= 0
            assert 0.0 < row["roofline_fraction"] <= 1.0
    # the gauges rode along
    gauges = obs.snapshot()["gauges"]
    assert any(r["labels"]["site"] == "batch_engine"
               for r in gauges["rb_roofline_fraction"])
    assert any(r["labels"]["site"] == "multiset"
               for r in gauges["rb_achieved_bytes_per_s"])


def test_cost_reset_snapshot_symmetry_and_prometheus():
    baseline = obs.snapshot()
    assert baseline["cost"]["sites"] == {}
    # fresh engine: its compile + first execute land after the reset, so
    # every new family (compile, first-query) is present in the render
    bms = datasets.synthetic_bitmaps(8, seed=44, universe=1 << 16,
                                     density=0.02)
    BatchEngine.from_bitmaps(bms).execute(random_query_pool(8, 8))
    snap = obs.snapshot()
    assert snap["cost"]["sites"]
    text = obs.render_prometheus()
    for family in ("rb_roofline_fraction", "rb_achieved_bytes_per_s",
                   "rb_device_time_seconds_total", "rb_compile_seconds",
                   "rb_first_query_seconds"):
        assert family in text, family
    obs.reset()
    after = obs.snapshot()
    # symmetric for everything reset() owns; the pull-model collectors
    # (live HBM ledger, cache sizes) keep reporting the still-resident
    # engine by design
    assert after["cost"] == baseline["cost"]
    assert after["counters"] == {} and after["histograms"] == {}


def test_cost_event_rides_dispatch_span(engine, pool, tmp_path):
    obs.enable(str(tmp_path / "t.jsonl"))
    try:
        engine.execute(pool[:8])
    finally:
        obs.disable()
    spans = _read_trace(tmp_path / "t.jsonl")
    evs = [ev for s in spans if s["name"] == "batch.dispatch"
           for ev in s["events"] if ev["name"] == "batch.cost"]
    assert evs and evs[0]["bytes_accessed"] > 0
    assert 0.0 < evs[0]["roofline_fraction"] <= 1.0


def test_estimate_seconds_calibrates_to_observed(engine, pool):
    peaks = obs_cost.device_peaks()
    est_peak = obs_cost.estimate_seconds(0.0, peaks["peak_bytes_per_s"])
    assert est_peak == pytest.approx(1.0)
    engine.execute(pool[:8])             # records achieved rates
    rates = obs_cost.TRACKER.observed_rates("batch_engine", "xla")
    assert rates is not None and rates["achieved_bytes_per_s"] > 0
    est = obs_cost.estimate_seconds(0.0, rates["achieved_bytes_per_s"],
                                    "batch_engine", "xla")
    assert est == pytest.approx(1.0)     # calibrated to the observed rate


def test_explain_reports_per_bucket_device_time(engine, pool):
    """Acceptance: explain() carries per-bucket estimated device time
    from the roofline model (and stays deterministic + serializable)."""
    rep = engine.explain(pool)
    assert "cost" in rep
    cost = rep["cost"]
    assert len(cost["per_bucket_est_device_ms"]) == len(rep["buckets"])
    assert all(b["est_device_ms"] > 0 for b in rep["buckets"])
    assert all(b["est_word_ops"] > 0 for b in rep["buckets"])
    assert cost["est_device_total_ms"] >= sum(
        cost["per_bucket_est_device_ms"]) - 1e-6
    json.loads(json.dumps(rep))
    assert rep == engine.explain(pool)


# ----------------------------------------------------------- SLO / phases

def test_phase_breakdown_sums_to_wall(engine, pool):
    """Acceptance: the per-phase breakdown (residual included) sums to
    within 5% of the query's wall time."""
    with obs_slo.attribution():
        engine.execute(pool)
    lq = obs_slo.last_query
    assert lq["site"] == "batch_engine" and lq["engine"] != "unresolved"
    total = sum(lq["phases_ms"].values())
    assert abs(total - lq["wall_ms"]) <= 0.05 * lq["wall_ms"] + 0.5, lq
    assert {"dispatch", "sync", "readback", "other"} <= set(
        lq["phases_ms"])
    # phase histograms populated per (site, engine, phase)
    rows = obs.snapshot()["histograms"]["rb_phase_seconds"]
    keys = {(r["labels"]["site"], r["labels"]["phase"]) for r in rows}
    assert ("batch_engine", "dispatch") in keys
    assert ("batch_engine", "other") in keys


def test_slo_miss_counted_and_traced(engine, pool, tmp_path):
    """A deadline no execute can make -> rb_slo_missed_total and a
    phase-attributed slo event on the batch.execute span."""
    policy = guard.GuardPolicy(slo_deadline_ms=1e-4)
    obs.enable(str(tmp_path / "slo.jsonl"))
    try:
        engine.execute(pool[:8], policy=policy)
    finally:
        obs.disable()
    snap = obs.snapshot()
    missed = snap["counters"]["rb_slo_missed_total"]
    assert missed[0]["labels"]["site"] == "batch_engine"
    assert missed[0]["value"] == 1
    assert "rb_slo_attained_total" not in snap["counters"]
    spans = _read_trace(tmp_path / "slo.jsonl")
    evs = [ev for s in spans if s["name"] == "batch.execute"
           for ev in s["events"] if ev["name"] == "slo"]
    assert evs and evs[0]["missed"] is True
    total = sum(evs[0]["phases_ms"].values())
    assert abs(total - evs[0]["wall_ms"]) \
        <= 0.05 * evs[0]["wall_ms"] + 0.5


def test_slo_attained_and_reconciles_with_guard_stats(engine, pool):
    """Attained + missed == guarded executes, also under an injected
    fault schedule whose retries slow the query past its deadline."""
    generous = guard.GuardPolicy(slo_deadline_ms=1e7)
    engine.execute(pool[:4], policy=generous)
    engine.execute(pool[:4], policy=generous)
    # injected transient faults: retries + backoff blow a tight deadline
    tight = guard.GuardPolicy(slo_deadline_ms=1e-4)
    with faults.inject("transient@xla=1.0:0xD1"):
        engine.execute(pool[:4], policy=tight)
    snap = obs.snapshot()["counters"]

    def total(name):
        return sum(r["value"] for r in snap.get(name, [])
                   if r["labels"].get("site") == "batch_engine")

    assert total("rb_slo_attained_total") == 2
    assert total("rb_slo_missed_total") == 1
    # reconciliation: every SLO-accounted execute is a guarded dispatch,
    # and the injected run's retries/demotions are visible in the same
    # stats the counters must agree with
    stats = guard.dispatch_stats("batch_engine")
    assert stats["retries"] > 0 or stats["demotions"] > 0
    ev = {(r["labels"]["site"], r["labels"]["event"]): r["value"]
          for r in snap["rb_dispatch_events_total"]}
    assert ev[("batch_engine", "retries")] == stats["retries"]
    assert ev[("batch_engine", "demotions")] == stats["demotions"]


def test_multiset_slo_and_env_knob(monkeypatch):
    """ROARING_TPU_SLO_MS reaches the pooled engine through
    GuardPolicy.from_env, counted at the multiset site."""
    tenants = [datasets.synthetic_bitmaps(8, seed=60 + i,
                                          universe=1 << 16, density=0.01)
               for i in range(2)]
    ms = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    pool = random_multiset_pool([8] * 2, 8, seed=3)
    monkeypatch.setenv(guard.ENV_SLO_MS, "1e-4")
    ms.execute(pool)
    monkeypatch.delenv(guard.ENV_SLO_MS)
    missed = obs.snapshot()["counters"]["rb_slo_missed_total"]
    assert any(r["labels"]["site"] == "multiset" and r["value"] >= 1
               for r in missed)


def test_queue_phase_from_enqueued_at():
    """A serving loop passing arrival time gets the queue wait attributed
    (the ROADMAP item 2 vocabulary)."""
    import time

    t_arrival = time.perf_counter()
    time.sleep(0.02)
    with obs_slo.query("batch_engine", deadline_ms=1e7,
                       enqueued_at=t_arrival):
        pass
    lq = obs_slo.last_query
    assert lq["phases_ms"]["queue"] >= 15.0
    assert lq["wall_ms"] >= lq["phases_ms"]["queue"]


def test_nested_query_contexts_suppressed():
    with obs_slo.attribution():
        with obs_slo.query("multiset") as outer:
            inner = obs_slo.query("batch_engine")
            assert inner is obs_slo._NOOP
            assert outer is not obs_slo._NOOP
    assert obs_slo.last_query["site"] == "multiset"


def test_profile_on_slo_miss_env_parsing(monkeypatch):
    monkeypatch.setenv(obs_slo.ENV_PROFILE, "/tmp/x:3")
    obs_slo.refresh_from_env()
    assert obs_slo._profile_dir == "/tmp/x"
    assert obs_slo._profile_budget == 3
    monkeypatch.setenv(obs_slo.ENV_PROFILE, "/tmp/y")
    obs_slo.refresh_from_env()
    assert obs_slo._profile_dir == "/tmp/y"
    assert obs_slo._profile_budget == 1
    monkeypatch.delenv(obs_slo.ENV_PROFILE)
    obs_slo.refresh_from_env()
    assert obs_slo._profile_dir is None


# ------------------------------------------------------ cold-path export

def test_compile_seconds_hit_miss_and_first_query():
    bms = datasets.synthetic_bitmaps(8, seed=33, universe=1 << 16,
                                     density=0.02)
    eng = BatchEngine.from_bitmaps(bms)
    qs = random_query_pool(8, 8)
    eng.execute(qs)                    # miss: compiles
    eng.execute(qs)                    # hit: cached program
    snap = obs.snapshot()["histograms"]
    rows = {(r["labels"]["site"], r["labels"]["cache"]): r
            for r in snap["rb_compile_seconds"]}
    assert rows[("batch_engine", "miss")]["count"] >= 1
    assert rows[("batch_engine", "hit")]["count"] >= 1
    # the miss paid a real compile; the hit is a cache lookup
    miss = rows[("batch_engine", "miss")]
    hit = rows[("batch_engine", "hit")]
    assert miss["sum"] / miss["count"] > hit["sum"] / hit["count"]
    fq = snap["rb_first_query_seconds"]
    assert any(r["labels"]["site"] == "batch_engine" and r["count"] == 1
               for r in fq)
    # ingest build exported too (the set construction above)
    assert any(r["count"] >= 1
               for r in snap["rb_ingest_build_seconds"])


# ------------------------------------------------------------- the tools

def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchDiffLaneChanges:
    def test_added_removed_lanes(self):
        bd = _load_tool("bench_diff")
        old = {"a.qps": 1.0, "gone.pack_ms": 2.0, "shared.val": 3.0}
        new = {"a.qps": 1.1, "shared.val": 3.0, "fresh.qps": 9.0}
        added, removed = bd.lane_changes(old, new)
        assert added == ["fresh.qps"]
        assert removed == ["gone.pack_ms"]

    def test_phase_ms_lanes_are_neutral(self):
        """Single-sample phase attribution must never gate: a residual
        phase doubling between rounds is noise, and time moving between
        phases is not a regression."""
        bd = _load_tool("bench_diff")
        assert bd.direction("phase_ms.census1881.other") == 0
        assert bd.direction("phase_ms.census1881.dispatch") == 0
        # the roofline fraction trends but is not *_x-ambiguous either:
        # informational (no directional token matches)
        assert bd.direction("cost.census1881") == 0


class TestBenchSentry:
    def _rounds(self, lanes_by_round):
        return [(f"r{i:02d}", lanes)
                for i, lanes in enumerate(lanes_by_round, 1)]

    def test_clean_trajectory(self):
        bs = _load_tool("bench_sentry")
        rounds = self._rounds([
            {"q64_e2e_qps": 1000.0, "pack_ms": 5.0},
            {"q64_e2e_qps": 1050.0, "pack_ms": 4.8},
            {"q64_e2e_qps": 1100.0, "pack_ms": 4.9},
        ])
        series = bs.build_series(rounds)
        a = bs.analyze(series, [n for n, _ in rounds], 0.15, 0.15)
        assert a["step_regressions"] == []
        assert a["drift_regressions"] == []

    def test_flags_20pct_qps_step(self):
        """Acceptance: a synthetic 20% QPS step regression in the newest
        round is flagged (and a historical step is not gated)."""
        bs = _load_tool("bench_sentry")
        rounds = self._rounds([
            {"q64_e2e_qps": 1000.0}, {"q64_e2e_qps": 1010.0},
            {"q64_e2e_qps": 808.0},          # -20% step
        ])
        series = bs.build_series(rounds)
        a = bs.analyze(series, [n for n, _ in rounds], 0.15, 0.15)
        assert a["step_regressions"] == ["q64_e2e_qps"]
        # same step one round earlier, recovered since: history, not gate
        rounds = self._rounds([
            {"q64_e2e_qps": 1000.0}, {"q64_e2e_qps": 800.0},
            {"q64_e2e_qps": 1000.0},
        ])
        series = bs.build_series(rounds)
        a = bs.analyze(series, [n for n, _ in rounds], 0.15, 0.15)
        assert a["step_regressions"] == []
        assert a["lanes"]["q64_e2e_qps"]["steps"]   # recorded as history

    def test_flags_monotone_drift(self):
        """Four rounds each -8% (under any per-step threshold) gate as
        drift: the slow bleed a pairwise diff never fires on."""
        bs = _load_tool("bench_sentry")
        vals = [1000.0, 920.0, 846.0, 778.0, 716.0]
        rounds = self._rounds([{"q64_e2e_qps": v} for v in vals])
        series = bs.build_series(rounds)
        a = bs.analyze(series, [n for n, _ in rounds], 0.15, 0.15)
        assert a["step_regressions"] == []
        assert a["drift_regressions"] == ["q64_e2e_qps"]
        assert a["lanes"]["q64_e2e_qps"]["drift"] < -0.15

    def test_removed_lane_noticed(self, tmp_path):
        bs = _load_tool("bench_sentry")
        bd = _load_tool("bench_diff")
        old = {"q64_e2e_qps": 1000.0, "fault_lane.qps_clean": 500.0}
        new = {"q64_e2e_qps": 1001.0}
        added, removed = bd.lane_changes(old, new)
        assert removed == ["fault_lane.qps_clean"] and added == []
        # end to end through main(): verdict lists it; --fail stays 0,
        # --fail-removed gates
        import sys

        p1, p2 = tmp_path / "r1.json", tmp_path / "r2.json"
        p1.write_text(json.dumps(old))
        p2.write_text(json.dumps(new))
        argv = sys.argv
        try:
            sys.argv = ["bench_sentry", str(p1), str(p2), "--fail"]
            assert bs.main() == 0
            sys.argv = ["bench_sentry", str(p1), str(p2), "--fail",
                        "--fail-removed"]
            assert bs.main() == 1
        finally:
            sys.argv = argv

    def test_unusable_round_skipped(self, tmp_path):
        """An r01-class driver capture (traceback tail, parsed null) is
        recorded unusable, not fatal."""
        bs = _load_tool("bench_sentry")
        bad = {"n": 1, "cmd": "x", "rc": 1, "tail": "Traceback ...\n",
               "parsed": None}
        good = {"q64_e2e_qps": 1000.0}
        paths = []
        for i, doc in enumerate([bad, good, good]):
            p = tmp_path / f"BENCH_r{i + 1:02d}.json"
            p.write_text(json.dumps(doc))
            paths.append(str(p))
        rounds, unusable = bs.load_rounds(paths)
        assert unusable == ["BENCH_r01"]
        assert [n for n, _ in rounds] == ["BENCH_r02", "BENCH_r03"]

    def test_committed_trajectory_passes_clean(self):
        """Acceptance: the sentry gate over the checked-in r01..r05
        files is clean (r01 unusable by design)."""
        import glob

        bs = _load_tool("bench_sentry")
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r[0-9]*.json")))
        assert len(paths) >= 5
        rounds, unusable = bs.load_rounds(paths)
        assert "BENCH_r01" in unusable
        series = bs.build_series(rounds)
        a = bs.analyze(series, [n for n, _ in rounds], 0.25, 0.25)
        assert a["step_regressions"] == []
        assert a["drift_regressions"] == []

    def test_markdown_table_renders(self):
        bs = _load_tool("bench_sentry")
        rounds = self._rounds([
            {"q64_e2e_qps": 1000.0}, {"q64_e2e_qps": 700.0}])
        series = bs.build_series(rounds)
        names = [n for n, _ in rounds]
        a = bs.analyze(series, names, 0.15, 0.15)
        md = bs.markdown_table(series, names, a)
        assert "q64_e2e_qps" in md and "STEP" in md
        assert md.splitlines()[0].startswith("| lane |")


# --------------------------------------------------- check_trace schemas

class TestCheckTraceCostSlo:
    def test_validates_cost_and_slo_events(self, engine, pool, tmp_path):
        path = tmp_path / "dump.jsonl"
        obs.enable(str(path))
        try:
            engine.execute(pool[:8],
                           policy=guard.GuardPolicy(slo_deadline_ms=1e-4))
        finally:
            obs.disable()
        ct = _load_tool("check_trace")
        assert ct.validate(str(path)) == []

    def test_rejects_bad_cost_and_slo_events(self, tmp_path):
        ct = _load_tool("check_trace")
        bad = tmp_path / "bad.jsonl"
        span = {"name": "batch.dispatch", "span_id": "a-1",
                "parent_id": None, "trace_id": "a-1", "pid": 1,
                "t_start": 0.0, "dur_ms": 1.0, "tags": {},
                "events": [
                    {"name": "batch.cost", "t_offset_ms": 0.1,
                     "device_ms": -1, "roofline_fraction": 1.7},
                    {"name": "slo", "t_offset_ms": 0.2, "wall_ms": 100.0,
                     "phases_ms": {"dispatch": 10.0}},
                ]}
        bad.write_text(json.dumps(span) + "\n")
        errs = ct.validate(str(bad))
        assert any("device_ms" in e for e in errs)
        assert any("roofline_fraction" in e for e in errs)
        assert any("not within 5%" in e for e in errs)
