"""bench.py driver contract: the batched lane runs end-to-end on a small
resident set, and the stdout summary is one compact parseable JSON line
(VERDICT r5 weak #1 — the full document overflowed the driver's bounded
tail capture for two rounds running)."""

import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import bench  # noqa: E402
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet  # noqa: E402
from roaringbitmap_tpu.utils import datasets  # noqa: E402


@pytest.fixture(scope="module")
def small_state(monkeypatch_module=None):
    bms = datasets.synthetic_bitmaps(16, seed=2, universe=1 << 18,
                                     density=0.01)
    return {"ds": DeviceBitmapSet(bms)}


def test_batched_phase_small(small_state, monkeypatch):
    monkeypatch.setattr(bench, "BATCH_SIZES", (1, 4, 8))
    monkeypatch.setattr(bench, "BATCH_R", (2, 6))
    row = bench.batched_phase(small_state)
    assert row["parity_checked_queries"] > 0
    assert row["q1_seq_dispatch_qps"] > 0
    assert row["q8_e2e_qps"] > 0
    assert "q8_steady_qps" in row
    # the amortization INEQUALITY is asserted only in the dispatch-floor
    # proxy below (slow lane): on a work-dominated workload under CI load
    # the e2e comparison is noise, not signal


@pytest.mark.slow
def test_dispatch_floor_amortization_proxy():
    """Acceptance: Q=64 queries/sec >= 5x the Q=1 one-query-per-dispatch
    rate.  CPU proxy: per-query device work must be small relative to the
    dispatch floor (that is the regime the batch engine exists for — on
    the TPU lane census1881's ~10 us/op marginal sits under a 35-81 us
    dispatch floor); tiny single-key bitmaps isolate the floor here."""
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap

    from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                         random_query_pool)

    rng = np.random.default_rng(1)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 16, 500).astype(np.uint32))
        for _ in range(64)]
    eng = BatchEngine.from_bitmaps(bms)
    # small subsets: per-query work stays well under the per-dispatch cost
    pool = random_query_pool(64, 64, max_operands=3)
    t1 = min(_timed(lambda: eng.cardinalities(pool[:1])) for _ in range(5))
    t64 = min(_timed(lambda: eng.cardinalities(pool)) for _ in range(5))
    q1_rate, q64_rate = 1.0 / t1, 64.0 / t64
    # chained steady state is the amortization ceiling; e2e includes the
    # one dispatch being amortized
    fn = eng.chained_cardinality(pool, 32)
    expected = sum(int(c) for c in eng.cardinalities(pool))
    assert int(np.asarray(fn())) == (32 * expected) % 2**32
    t_steady = min(_timed(lambda: np.asarray(fn())) for _ in range(3)) / 32
    best_q64 = max(q64_rate, 64.0 / t_steady)
    assert best_q64 >= 5.0 * q1_rate, (q1_rate, q64_rate, 64.0 / t_steady)


def _timed(fn):
    import time

    fn()
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def test_multiset_phase_small(monkeypatch):
    """The cross-tenant lane (ISSUE 5) runs end-to-end at toy sizes:
    pooled-vs-per-set cells per (S, Q), a pipelined cell with an overlap
    ratio, and the compact headline the summary line carries."""
    monkeypatch.setattr(bench, "MULTISET_S", (1, 2))
    monkeypatch.setattr(bench, "MULTISET_Q", (4,))
    row = bench.multiset_phase()
    for cell in ("s1_q4", "s2_q4"):
        assert row[cell]["pooled_qps"] > 0
        assert row[cell]["per_set_qps"] > 0
        assert row[cell]["pooled_vs_per_set_x"] > 0
    assert "hbm" in row["s2_q4"]          # pooled predicted-vs-measured
    assert row["s2_q4"]["hbm"]["sets"] == 2
    pipe = row["s2_pipeline"]
    assert pipe["launches"] == 4 and 0.0 <= pipe["overlap_ratio"] <= 1.0
    assert row["headline"]["pooled_vs_per_set_x"] \
        == row["s2_q4"]["pooled_vs_per_set_x"]
    assert row["headline"]["overlap_ratio"] == pipe["overlap_ratio"]


def test_summary_is_one_small_line(tmp_path):
    doc = {
        "metric": "wide_or_census1881_aggregations_per_sec",
        "value": 76628.4, "vs_baseline": 67.9,
        "unit": "wide-OR/s (...)",
        "detail": {
            "backend": "tpu",
            "north_star": {
                "census1881": {"vs_baseline": 67.9, "target": 10.0,
                               "met": True},
                "wikileaks-noquotes": {"vs_baseline": 29.1, "target": 10.0,
                                       "met": True}},
            "north_star_spread": {
                "census1881": {"n": 5, "marginal_us_median": 13.05,
                               "marginal_us_min": 12.98,
                               "marginal_us_max": 13.1,
                               "samples_us": [13.05] * 5},
                "backend": "tpu"},
            "huge_filler": "x" * 8000,
        },
        "batched_by_dataset": {
            "census1881": {"q1_seq_dispatch_qps": 14000.0,
                           "q8_e2e_qps": 90000.0,
                           "q64_e2e_qps": 400000.0,
                           "q256_e2e_qps": 700000.0,
                           "q64_steady_qps": 900000.0,
                           "q64_vs_q1_amortization_x": 28.6,
                           "meets_5x": True}},
        "multiset": {
            "tenant_bitmaps": 8,
            "s4_q64": {"pooled_qps": 60000.0, "per_set_qps": 18000.0,
                       "pooled_vs_per_set_x": 3.3,
                       "hbm": {"q": 64, "sets": 4, "predicted_mb": 1.2}},
            "s4_pipeline": {"launches": 4, "overlap_ratio": 0.7},
            "headline": {"pooled_vs_per_set_x": 3.3,
                         "overlap_ratio": 0.7}},
    }
    s = bench.build_summary(doc, str(tmp_path / "bench_full.json"))
    line = json.dumps(s, separators=(",", ":"))
    assert "\n" not in line and len(line) < 1500, len(line)
    parsed = json.loads(line)
    assert parsed["north_star"]["census1881"]["met"] is True
    assert parsed["batched_qps"]["census1881"]["meets_5x"] is True
    # multiset lane rides compactly: [pooled_qps, per_set_qps, ratio]
    assert parsed["multiset"]["s4_q64"] == [60000.0, 18000.0, 3.3]
    assert parsed["multiset"]["overlap_ratio"] == 0.7
    assert parsed["marginal_us_median"]["census1881"] == 13.05
    assert parsed["full_doc"].endswith("bench_full.json")
    # the emitted line is the capped form and keeps the optional fields
    # when the document is normal-sized
    capped = bench.summary_line(doc, str(tmp_path / "bench_full.json"))
    assert capped == line
    assert len(capped.encode()) <= bench.SUMMARY_MAX_BYTES


def test_cost_slo_fields_ride_summary_and_shed_first(tmp_path):
    """ISSUE 6: the batched lane's roofline fraction + per-phase
    breakdown ride the capped summary when it fits, and are the FIRST
    fields the byte-cap ladder sheds — the driver-gate core and the
    older lanes must survive them under adversarial bloat."""
    full = str(tmp_path / "bench_full.json")
    doc = _bloated_doc(2)
    for row in doc["batched_by_dataset"].values():
        row["cost"] = {"roofline_fraction": 0.42, "achieved_gbps": 3.1,
                       "device_ms": 1.9}
        row["phase_ms"] = {"plan": 0.4, "dispatch": 1.1, "sync": 0.7,
                           "readback": 0.3, "other": 0.1}
    line = bench.summary_line(doc, full)
    parsed = json.loads(line)
    assert parsed["cost"]["dataset-000"] == 0.42
    assert parsed["phase_ms"]["dataset-000"]["dispatch"] == 1.1
    assert bench.SUMMARY_DROP_ORDER[:2] == ("phase_ms", "cost")
    # adversarial: enough datasets that the cap forces shedding — the
    # cost/phase fields go first, the core survives, the cap holds
    doc = _bloated_doc(40)
    for row in doc["batched_by_dataset"].values():
        row["cost"] = {"roofline_fraction": 0.42}
        row["phase_ms"] = {"dispatch": 1.1, "other": 0.1}
    line = bench.summary_line(doc, full)
    assert len(line.encode("utf-8")) <= bench.SUMMARY_MAX_BYTES
    parsed = json.loads(line)
    assert "cost" not in parsed and "phase_ms" not in parsed
    assert parsed["value"] == 1.0 and parsed["vs_baseline"] == 2.0


def _bloated_doc(n_datasets: int) -> dict:
    """A document whose naive summary would overflow any bounded tail
    capture: many datasets, each with full spread + batched rows."""
    names = [f"dataset-{i:03d}" for i in range(n_datasets)]
    return {
        "metric": "wide_or_dataset-000_aggregations_per_sec",
        "value": 1.0, "vs_baseline": 2.0, "unit": "wide-OR/s (...)",
        "detail": {
            "backend": "tpu",
            "north_star": {n: {"vs_baseline": 12.3, "target": 10.0,
                               "met": True} for n in names},
            "north_star_spread": {
                **{n: {"n": 5, "marginal_us_median": 13.05,
                       "marginal_us_min": 12.98, "marginal_us_max": 13.1,
                       "samples_us": [13.05] * 5} for n in names},
                "backend": "tpu"},
        },
        "batched_by_dataset": {
            n: {"q1_seq_dispatch_qps": 14000.0, "q8_e2e_qps": 90000.0,
                "q64_e2e_qps": 400000.0, "q256_e2e_qps": 700000.0,
                "q64_steady_qps": 900000.0,
                "q64_vs_q1_amortization_x": 28.6, "meets_5x": True,
                "fault_lane": {"demotion_overhead_x": 1.4,
                               "sequential_floor_cost_x": 60.0}}
            for n in names},
    }


def test_summary_line_holds_byte_cap_under_bloat(tmp_path):
    """ADVICE r5: the driver's bounded tail capture truncated the summary
    head for two rounds.  summary_line must stay under the fixed byte
    budget for ANY document by shedding optional fields, while remaining
    one line of valid JSON with the driver-gate core intact."""
    full = str(tmp_path / "bench_full.json")
    for n in (2, 8, 40):
        line = bench.summary_line(_bloated_doc(n), full)
        assert len(line.encode("utf-8")) <= bench.SUMMARY_MAX_BYTES, \
            (n, len(line))
        assert "\n" not in line
        parsed = json.loads(line)
        assert parsed["metric"] == "wide_or_dataset-000_aggregations_per_sec"
        assert parsed["value"] == 1.0 and parsed["vs_baseline"] == 2.0
    # normal-sized docs shed nothing
    small = bench.summary_line(_bloated_doc(2), full)
    assert "batched_qps" in json.loads(small)
