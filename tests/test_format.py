"""Serialization tests: byte-exact interop with the reference corpus.

The reference's own serialized fixtures (TestAdversarialInputs.java:17-63)
are read directly from the read-only mirror — they are the ground truth the
Java implementation produced."""

import glob
import os

import numpy as np
import pytest

from roaringbitmap_tpu import InvalidRoaringFormat, RoaringBitmap
from roaringbitmap_tpu.format import spec

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_corpus = pytest.mark.skipif(not os.path.isdir(TESTDATA),
                                  reason="reference corpus not mounted")


@needs_corpus
@pytest.mark.parametrize("name", ["bitmapwithruns.bin", "bitmapwithoutruns.bin"])
def test_reference_fixture_roundtrip_byte_identical(name):
    raw = open(os.path.join(TESTDATA, name), "rb").read()
    rb = RoaringBitmap.deserialize(raw)
    assert rb.cardinality == 200100  # TestAdversarialInputs.java expected card
    assert rb.serialize() == raw


@needs_corpus
def test_adversarial_corpus_rejected_cleanly():
    for path in sorted(glob.glob(os.path.join(TESTDATA, "crashproneinput*.bin"))):
        with pytest.raises(InvalidRoaringFormat):
            RoaringBitmap.deserialize(open(path, "rb").read())


def test_roundtrip_randomized(rng):
    for _ in range(10):
        n = int(rng.integers(1, 200000))
        vals = rng.integers(0, 1 << 28, n).astype(np.uint32)
        rb = RoaringBitmap.from_values(vals)
        if rng.integers(2):
            rb.run_optimize()
        raw = rb.serialize()
        back = RoaringBitmap.deserialize(raw)
        assert back == rb
        assert back.serialize() == raw
        assert len(raw) == rb.serialized_size_in_bytes()


def test_size_upper_bound(rng):
    vals = rng.integers(0, 1 << 24, 100000).astype(np.uint32)
    rb = RoaringBitmap.from_values(vals)
    bound = spec.maximum_serialized_size(rb.cardinality, 1 << 24)
    assert rb.serialized_size_in_bytes() <= bound


def test_empty_and_tiny():
    e = RoaringBitmap()
    assert RoaringBitmap.deserialize(e.serialize()) == e
    t = RoaringBitmap.bitmap_of(7)
    assert RoaringBitmap.deserialize(t.serialize()).to_array().tolist() == [7]
    # run container with size < NO_OFFSET_THRESHOLD exercises the no-offsets branch
    r = RoaringBitmap.from_range(10, 50000)
    r.run_optimize()
    assert r.has_run_compression()
    assert RoaringBitmap.deserialize(r.serialize()) == r


def test_garbage_rejected():
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x00" * 64)
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x3a\x30")  # truncated cookie


# ---- malformed-input hardening (robustness satellite): every lie class
# raises InvalidRoaringFormat — also exported as runtime.errors.CorruptInput
# — never a raw numpy/struct error and never a silently-corrupt container.

def _no_run_header(size: int) -> bytes:
    return (np.uint32(spec.SERIAL_COOKIE_NO_RUNCONTAINER
                      ).astype("<u4").tobytes()
            + np.uint32(size).astype("<u4").tobytes())


def test_corrupt_input_is_the_runtime_alias():
    from roaringbitmap_tpu.runtime import errors

    assert errors.CorruptInput is InvalidRoaringFormat


def test_out_of_order_keys_rejected():
    rb = RoaringBitmap.from_values(
        np.array([1, 70000, 140000], dtype=np.uint32))
    b = bytearray(rb.serialize())
    b[8:10], b[12:14] = b[12:14], b[8:10]   # swap first two keys
    with pytest.raises(InvalidRoaringFormat, match="not strictly"):
        RoaringBitmap.deserialize(bytes(b))


def test_bitmap_cardinality_lie_rejected():
    rb = RoaringBitmap.from_values(np.arange(0, 30000, 2, dtype=np.uint32))
    b = bytearray(rb.serialize())
    b[10] = (b[10] + 1) & 0xFF              # declared card of container 0
    with pytest.raises(InvalidRoaringFormat, match="declared cardinality"):
        RoaringBitmap.deserialize(bytes(b))


def test_unsorted_array_payload_rejected():
    b = (_no_run_header(1) + np.array([7, 2], dtype="<u2").tobytes()
         + np.uint32(16).astype("<u4").tobytes()
         + np.array([5, 3, 9], dtype="<u2").tobytes())
    with pytest.raises(InvalidRoaringFormat, match="strictly increasing"):
        RoaringBitmap.deserialize(b)


def test_run_lies_rejected():
    rhdr = (np.uint32(spec.SERIAL_COOKIE).astype("<u4").tobytes()
            + bytes([1]))
    # overlapping / out-of-order runs
    b = (rhdr + np.array([0, 9], dtype="<u2").tobytes()
         + np.uint16(2).astype("<u2").tobytes()
         + np.array([10, 4, 8, 4], dtype="<u2").tobytes())
    with pytest.raises(InvalidRoaringFormat, match="overlap"):
        RoaringBitmap.deserialize(b)
    # run extending past the 2^16 container end (length lie)
    b = (rhdr + np.array([0, 99], dtype="<u2").tobytes()
         + np.uint16(1).astype("<u2").tobytes()
         + np.array([65530, 99], dtype="<u2").tobytes())
    with pytest.raises(InvalidRoaringFormat, match="past 65535"):
        RoaringBitmap.deserialize(b)
    # zero runs while the descriptor declares cardinality 10
    b = (rhdr + np.array([0, 9], dtype="<u2").tobytes()
         + np.uint16(0).astype("<u2").tobytes())
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b)


def test_length_fields_past_buffer_end_rejected():
    rb = RoaringBitmap.from_values(
        np.array([1, 70000, 140000, 300000, 400000], dtype=np.uint32))
    blob = rb.serialize()
    desc_end = 8 + 4 * 5
    with pytest.raises(InvalidRoaringFormat, match="offset block"):
        RoaringBitmap.deserialize(blob[:desc_end + 6])  # inside offsets
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(blob[:len(blob) - 3])  # inside payload
    # array cardinality inflated so its payload reads past the buffer
    big = bytearray(blob)
    big[10] = 0x40                       # container 0 card-1 low byte
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(bytes(big))


def test_compression_rate_by_gap():
    """TestCompressionRates.SimpleCompressionRateTest: serialized bits per
    value stays below min(gap, 16) + 1 as density thins by powers of two —
    the size guarantee the container promote/demote thresholds exist for."""
    n = 500_000
    gap = 1
    while gap < 1024:
        # NO run_optimize, like the reference: the bound must hold from
        # the array/bitmap promote thresholds alone
        rb = RoaringBitmap.from_values(
            np.arange(0, n * gap, gap, dtype=np.uint32))
        bits_per_value = rb.serialized_size_in_bytes() * 8.0 / n
        assert bits_per_value < min(gap, 16) + 1, (gap, bits_per_value)
        gap *= 2
