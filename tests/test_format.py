"""Serialization tests: byte-exact interop with the reference corpus.

The reference's own serialized fixtures (TestAdversarialInputs.java:17-63)
are read directly from the read-only mirror — they are the ground truth the
Java implementation produced."""

import glob
import os

import numpy as np
import pytest

from roaringbitmap_tpu import InvalidRoaringFormat, RoaringBitmap
from roaringbitmap_tpu.format import spec

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_corpus = pytest.mark.skipif(not os.path.isdir(TESTDATA),
                                  reason="reference corpus not mounted")


@needs_corpus
@pytest.mark.parametrize("name", ["bitmapwithruns.bin", "bitmapwithoutruns.bin"])
def test_reference_fixture_roundtrip_byte_identical(name):
    raw = open(os.path.join(TESTDATA, name), "rb").read()
    rb = RoaringBitmap.deserialize(raw)
    assert rb.cardinality == 200100  # TestAdversarialInputs.java expected card
    assert rb.serialize() == raw


@needs_corpus
def test_adversarial_corpus_rejected_cleanly():
    for path in sorted(glob.glob(os.path.join(TESTDATA, "crashproneinput*.bin"))):
        with pytest.raises(InvalidRoaringFormat):
            RoaringBitmap.deserialize(open(path, "rb").read())


def test_roundtrip_randomized(rng):
    for _ in range(10):
        n = int(rng.integers(1, 200000))
        vals = rng.integers(0, 1 << 28, n).astype(np.uint32)
        rb = RoaringBitmap.from_values(vals)
        if rng.integers(2):
            rb.run_optimize()
        raw = rb.serialize()
        back = RoaringBitmap.deserialize(raw)
        assert back == rb
        assert back.serialize() == raw
        assert len(raw) == rb.serialized_size_in_bytes()


def test_size_upper_bound(rng):
    vals = rng.integers(0, 1 << 24, 100000).astype(np.uint32)
    rb = RoaringBitmap.from_values(vals)
    bound = spec.maximum_serialized_size(rb.cardinality, 1 << 24)
    assert rb.serialized_size_in_bytes() <= bound


def test_empty_and_tiny():
    e = RoaringBitmap()
    assert RoaringBitmap.deserialize(e.serialize()) == e
    t = RoaringBitmap.bitmap_of(7)
    assert RoaringBitmap.deserialize(t.serialize()).to_array().tolist() == [7]
    # run container with size < NO_OFFSET_THRESHOLD exercises the no-offsets branch
    r = RoaringBitmap.from_range(10, 50000)
    r.run_optimize()
    assert r.has_run_compression()
    assert RoaringBitmap.deserialize(r.serialize()) == r


def test_garbage_rejected():
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x00" * 64)
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x3a\x30")  # truncated cookie


def test_compression_rate_by_gap():
    """TestCompressionRates.SimpleCompressionRateTest: serialized bits per
    value stays below min(gap, 16) + 1 as density thins by powers of two —
    the size guarantee the container promote/demote thresholds exist for."""
    n = 500_000
    gap = 1
    while gap < 1024:
        # NO run_optimize, like the reference: the bound must hold from
        # the array/bitmap promote thresholds alone
        rb = RoaringBitmap.from_values(
            np.arange(0, n * gap, gap, dtype=np.uint32))
        bits_per_value = rb.serialized_size_in_bytes() * 8.0 / n
        assert bits_per_value < min(gap, 16) + 1, (gap, bits_per_value)
        gap *= 2
