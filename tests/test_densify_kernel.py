"""Chunked Pallas densify kernel (ops.kernels.densify_chunks_pallas) vs the
XLA scatter-add reference (ops.dense.densify_streams), plus the host chunk
prep (ops.packing.chunk_value_stream) and the compact-layout integration."""

import jax.numpy as jnp
import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.ops import dense, kernels, packing


def _scatter_oracle(streams_args, n_rows):
    dense_words, dense_dest, values, val_counts, val_dest = streams_args
    return np.asarray(dense.densify_streams(
        jnp.asarray(dense_words), jnp.asarray(dense_dest),
        jnp.asarray(values), jnp.asarray(val_counts),
        jnp.asarray(val_dest), n_rows, int(values.size)))


def _chunk_run(values, val_counts, val_dest, n_rows,
               dense_words=None, dense_dest=None):
    cv, cr = packing.chunk_value_stream(values, val_counts, val_dest, n_rows)
    live = np.zeros(n_rows + 1, np.uint32)
    live[cr] = 1
    out = kernels.densify_chunks_pallas(
        jnp.asarray(cv), jnp.asarray(cr), jnp.asarray(live), n_rows)
    if dense_words is not None and dense_words.shape[0]:
        out = out.at[jnp.asarray(dense_dest)].set(jnp.asarray(dense_words))
    return np.asarray(out)


def test_chunk_prep_shapes_and_padding():
    values = np.concatenate([np.arange(300, dtype=np.uint16),
                             np.array([7], np.uint16)])
    val_counts = np.array([300, 0, 1], np.int32)  # zero-count skipped
    val_dest = np.array([2, 3, 5], np.int32)
    cv, cr = packing.chunk_value_stream(values, val_counts, val_dest, 8)
    assert cv.shape[1] == packing.CHUNK_VALUES == kernels.DENSIFY_CHUNK
    assert cv.shape[0] & (cv.shape[0] - 1) == 0  # pow2 chunk count
    # 300 values -> 3 chunks of row 2, then 1 chunk of row 5
    assert cr[:4].tolist() == [2, 2, 2, 5]
    assert (cr[4:] == 8).all()  # padding chunks target the scratch row
    # partial-chunk padding is the sentinel, never a duplicated value
    assert (cv[2][300 - 256:] == packing.CHUNK_PAD).all()
    assert cv[3][0] == 7 and (cv[3][1:] == packing.CHUNK_PAD).all()


@pytest.mark.parametrize("seed", [0, 1])
def test_kernel_matches_scatter_reference(seed):
    rng = np.random.default_rng(seed)
    n_rows = 11
    rows = sorted(rng.choice(n_rows, size=6, replace=False))
    pieces = [np.unique(rng.integers(0, 65536, rng.integers(1, 4097))
                        .astype(np.uint16)) for _ in rows]
    values = np.concatenate(pieces)
    val_counts = np.array([p.size for p in pieces], np.int32)
    val_dest = np.array(rows, np.int32)
    dense_words = rng.integers(0, 1 << 32, (2, 2048)).astype(np.uint32)
    free = [r for r in range(n_rows) if r not in rows][:2]
    dense_dest = np.array(free, np.int32)
    args = (dense_words, dense_dest, values, val_counts, val_dest)
    want = _scatter_oracle(args, n_rows)
    got = _chunk_run(values, val_counts, val_dest, n_rows,
                     dense_words, dense_dest)
    assert np.array_equal(got, want)


def test_kernel_empty_and_single_value():
    got = _chunk_run(np.empty(0, np.uint16), np.empty(0, np.int32),
                     np.empty(0, np.int32), 3)
    assert got.shape == (3, 2048) and not got.any()
    got = _chunk_run(np.array([65535], np.uint16), np.array([1], np.int32),
                     np.array([1], np.int32), 3)
    assert got[1].view(np.uint64)[-1] == np.uint64(1) << np.uint64(63)
    assert got[0].sum() == 0 and got[2].sum() == 0


def test_kernel_full_container():
    """All 65536 bits of one row set — every byte-plane sum at its
    maximum, the exactness edge of the MXU accumulation."""
    values = np.arange(65536, dtype=np.uint16)
    got = _chunk_run(values, np.array([65536], np.int32),
                     np.array([0], np.int32), 2)
    assert (got[0] == 0xFFFFFFFF).all() and not got[1].any()


def test_compact_layout_uses_chunk_kernel():
    """DeviceBitmapSet compact: pallas engine rebuilds via the chunk
    kernel, pallas-nibble keeps the legacy fused path, xla the scatter —
    all three bit-exact with the host oracle."""
    from roaringbitmap_tpu.parallel import aggregation, fast_aggregation

    rng = np.random.default_rng(9)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 18, 4000).astype(np.uint32))
        for _ in range(10)]
    bms[0] = bms[0] | RoaringBitmap.from_values(
        np.arange(1 << 17, (1 << 17) + 30000, dtype=np.uint32))
    ds = aggregation.DeviceBitmapSet(bms, layout="compact")
    assert ds._chunks is not None
    for op, fn in (("or", fast_aggregation.or_),
                   ("xor", fast_aggregation.xor)):
        want = fn(*bms)
        for eng in ("pallas", "pallas-nibble", "xla"):
            assert ds.aggregate(op, engine=eng) == want, (op, eng)
    # chained probes through the chunk path stay loop-variant + bit-exact
    want_or = fast_aggregation.or_(*bms).cardinality
    got = int(np.asarray(ds.chained_wide_or(3, engine="pallas")(None)))
    assert got == (3 * want_or) % 2**32
    got = int(np.asarray(
        ds.chained_aggregate("or", 3, engine="pallas-nibble")(None)))
    assert got == (3 * want_or) % 2**32


def test_dense_block4_rung_parity():
    """Ultra-sparse key-heavy shapes (the uscensus2000 profile: mostly
    singleton segments) take the block-4 dense rung; parity must hold on
    both engines and the image must shrink vs block 8."""
    from roaringbitmap_tpu.parallel import aggregation, fast_aggregation

    rng = np.random.default_rng(3)
    # ~1 value per container, keys mostly disjoint -> median segment 1
    bms = [RoaringBitmap.from_values(np.unique(
        (rng.choice(500, size=25, replace=False).astype(np.uint32) << 16)
        + rng.integers(0, 65536, 25).astype(np.uint32)))
        for _ in range(12)]
    # this shape is exactly what the adaptive default flips to counts —
    # the block-4 rung under test is a property of the DENSE image, so
    # pin the explicit override (and assert the auto flip while here)
    from roaringbitmap_tpu.insights import analysis as insights
    assert insights.choose_layout(bms)["layout"] == "counts"
    ds = aggregation.DeviceBitmapSet(bms, layout="dense")
    assert ds.block == 4
    ds8 = aggregation.DeviceBitmapSet(bms, block=8, layout="dense")
    assert ds.words.nbytes < ds8.words.nbytes
    for op, fn in (("or", fast_aggregation.or_),
                   ("xor", fast_aggregation.xor)):
        want = fn(*bms)
        for eng in ("pallas", "xla"):
            assert ds.aggregate(op, engine=eng) == want, (op, eng)
    assert ds.aggregate("and") == fast_aggregation.and_(*bms)
    # counts/compact layouts must keep the NIBBLE_GROUP-divisible floor
    dsc = aggregation.DeviceBitmapSet(bms, layout="counts")
    assert dsc.block >= 8
    assert dsc.aggregate("or") == fast_aggregation.or_(*bms)


def test_row_src_metadata():
    """pack_blocked_compact must report each row's source bitmap (batch
    engine selector), identically for object and byte inputs."""
    bms = [RoaringBitmap.bitmap_of(1, 0x10001),
           RoaringBitmap.bitmap_of(2, 0x20002),
           RoaringBitmap.bitmap_of(3, 0x10003)]
    p_obj = packing.pack_blocked_compact(bms)
    p_byte = packing.pack_blocked_compact([b.serialize() for b in bms])
    for p in (p_obj, p_byte):
        assert p.row_src is not None and p.row_src.size == p.n_rows
        # key 0 -> sources {0,1,2}; key 1 -> {0,2}; key 2 -> {1}
        for seg, want in enumerate(([0, 1, 2], [0, 2], [1])):
            off = p.seg_offsets[seg]
            got = p.row_src[off:off + p.seg_sizes[seg]].tolist()
            assert got == want, (seg, got)
        live = p.row_src >= 0
        assert int(live.sum()) == 6
