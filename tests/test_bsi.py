"""BSI tests (SURVEY §2.4) — model-based against NumPy oracles, plus
host/device parity for the fused comparator and both serialization formats."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.bsi import DeviceBSI, Operation, RoaringBitmapSliceIndex
from roaringbitmap_tpu.bsi.slice_index import read_vlong, write_vlong


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0xB51)
    n = 20000
    cols = np.unique(rng.integers(0, 1 << 22, n, dtype=np.uint32))
    vals = rng.integers(0, 1 << 20, cols.size, dtype=np.int64)
    return cols, vals


@pytest.fixture(scope="module")
def bsi(data):
    cols, vals = data
    return RoaringBitmapSliceIndex.from_pairs(cols, vals)


def _oracle_filter(cols, vals, op, a, b=0):
    if op is Operation.EQ:
        m = vals == a
    elif op is Operation.NEQ:
        m = vals != a
    elif op is Operation.LT:
        m = vals < a
    elif op is Operation.LE:
        m = vals <= a
    elif op is Operation.GT:
        m = vals > a
    elif op is Operation.GE:
        m = vals >= a
    else:
        m = (vals >= a) & (vals <= b)
    return cols[m]


ALL_OPS = [Operation.EQ, Operation.NEQ, Operation.LT, Operation.LE,
           Operation.GT, Operation.GE]


class TestVint:
    @pytest.mark.parametrize("v", [0, 1, -1, 127, -112, 128, -113, 255, 256,
                                   1 << 20, -(1 << 20), 2**31 - 1, -(2**31)])
    def test_roundtrip(self, v):
        out = bytearray()
        write_vlong(out, v)
        got, pos = read_vlong(memoryview(bytes(out)), 0)
        assert got == v and pos == len(out)

    def test_single_byte_range(self):
        for v in (-112, 127, 0):
            out = bytearray()
            write_vlong(out, v)
            assert len(out) == 1


class TestHostBSI:
    def test_build_and_get(self, data, bsi):
        cols, vals = data
        assert bsi.cardinality == cols.size
        assert bsi.min_value == int(vals.min())
        assert bsi.max_value == int(vals.max())
        for i in range(0, cols.size, 2500):
            v, ok = bsi.get_value(int(cols[i]))
            assert ok and v == int(vals[i])
        assert bsi.get_value(0xDEAD0001)[1] is False
        got, exists = bsi.get_values(cols[:100])
        assert np.array_equal(got, vals[:100]) and exists.all()

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_compare_matches_oracle(self, data, bsi, op):
        cols, vals = data
        pred = int(np.median(vals))
        got = bsi.compare(op, pred).to_array()
        assert np.array_equal(got, _oracle_filter(cols, vals, op, pred))

    def test_range_matches_oracle(self, data, bsi):
        cols, vals = data
        a, b = int(np.quantile(vals, 0.25)), int(np.quantile(vals, 0.75))
        got = bsi.compare(Operation.RANGE, a, b).to_array()
        assert np.array_equal(got, _oracle_filter(cols, vals, Operation.RANGE, a, b))

    def test_min_max_pruning_paths(self, data, bsi):
        cols, vals = data
        assert bsi.compare(Operation.LT, int(vals.max()) + 10).cardinality == cols.size
        assert bsi.compare(Operation.GT, int(vals.max()) + 10).is_empty()
        assert bsi.compare(Operation.GE, 0).cardinality == cols.size

    def test_compare_with_found_set(self, data, bsi):
        cols, vals = data
        fs = RoaringBitmap.from_values(cols[::3])
        pred = int(np.median(vals))
        got = bsi.compare(Operation.GE, pred, found_set=fs).to_array()
        oracle = np.intersect1d(_oracle_filter(cols, vals, Operation.GE, pred),
                                cols[::3])
        assert np.array_equal(got, oracle)

    def test_sum(self, data, bsi):
        cols, vals = data
        total, count = bsi.sum()
        assert total == int(vals.sum()) and count == cols.size
        fs = RoaringBitmap.from_values(cols[:500])
        total, count = bsi.sum(fs)
        assert total == int(vals[:500].sum()) and count == 500

    def test_top_k(self, data, bsi):
        cols, vals = data
        k = 250
        got = bsi.top_k(k)
        assert got.cardinality == k
        kth = np.sort(vals)[-k]
        got_vals, _ = bsi.get_values(got.to_array())
        # every selected value must be >= the k-th largest value
        assert got_vals.min() >= kth - 0  # ties allowed at the boundary
        assert (got_vals >= kth).all()

    def test_set_value_updates(self):
        bsi = RoaringBitmapSliceIndex()
        bsi.set_value(10, 5)
        bsi.set_value(11, 300)
        bsi.set_value(10, 7)  # overwrite
        assert bsi.get_value(10) == (7, True)
        assert bsi.get_value(11) == (300, True)
        assert bsi.min_value <= 7 and bsi.max_value == 300

    def test_add_with_carry(self):
        a = RoaringBitmapSliceIndex.from_pairs(
            np.array([1, 2, 3], dtype=np.uint32),
            np.array([3, 7, 15], dtype=np.int64))
        b = RoaringBitmapSliceIndex.from_pairs(
            np.array([2, 3, 4], dtype=np.uint32),
            np.array([1, 1, 9], dtype=np.int64))
        a.add(b)
        assert a.get_value(1) == (3, True)
        assert a.get_value(2) == (8, True)    # 7+1 carries across all bits
        assert a.get_value(3) == (16, True)   # 15+1 grows the bit depth
        assert a.get_value(4) == (9, True)
        assert a.max_value == 16 and a.min_value == 3

    def test_merge_disjoint(self, data):
        cols, vals = data
        h = cols.size // 2
        a = RoaringBitmapSliceIndex.from_pairs(cols[:h], vals[:h])
        b = RoaringBitmapSliceIndex.from_pairs(cols[h:], vals[h:])
        a.merge(b)
        whole = RoaringBitmapSliceIndex.from_pairs(cols, vals)
        assert a == whole

    def test_merge_overlap_raises(self):
        a = RoaringBitmapSliceIndex.from_pairs(
            np.array([1], dtype=np.uint32), np.array([1], dtype=np.int64))
        with pytest.raises(ValueError):
            a.merge(a.clone())

    def test_transpose_with_count(self):
        cols = np.arange(10, dtype=np.uint32)
        vals = np.array([5, 5, 5, 9, 9, 2, 2, 2, 2, 7], dtype=np.int64)
        bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
        t = bsi.transpose_with_count()
        assert t.get_value(5) == (3, True)
        assert t.get_value(9) == (2, True)
        assert t.get_value(2) == (4, True)
        assert t.get_value(7) == (1, True)
        assert t.get_value(4)[1] is False

    def test_in_values(self, data, bsi):
        cols, vals = data
        wanted = {int(vals[5]), int(vals[100])}
        got = bsi.in_values(wanted).to_array()
        oracle = cols[np.isin(vals, sorted(wanted))]
        assert np.array_equal(got, oracle)

    def test_to_pair_list(self):
        cols = np.array([3, 9], dtype=np.uint32)
        vals = np.array([40, 2], dtype=np.int64)
        bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
        assert bsi.to_pair_list() == [(3, 40), (9, 2)]

    def test_negative_value_rejected(self):
        with pytest.raises(ValueError):
            RoaringBitmapSliceIndex.from_pairs(
                np.array([1], dtype=np.uint32), np.array([-4], dtype=np.int64))

    def test_stream_serialization_roundtrip(self, bsi):
        data = bsi.serialize_stream()
        back = RoaringBitmapSliceIndex.deserialize_stream(data)
        assert back == bsi

    def test_buffer_serialization_roundtrip(self, bsi):
        data = bsi.serialize_buffer()
        assert len(data) == bsi.serialized_size_in_bytes()
        back = RoaringBitmapSliceIndex.deserialize_buffer(data)
        assert back == bsi


class TestImmutableBSI:
    """bsi/buffer tier (ImmutableBitSliceIndex.java:181, BitSliceIndexBase):
    attach to serialized bytes, full read-only query surface, no full parse."""

    @pytest.fixture(scope="class")
    def imm(self, bsi):
        from roaringbitmap_tpu.bsi import ImmutableBitSliceIndex

        return ImmutableBitSliceIndex(bsi.serialize_buffer())

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_compare_parity(self, data, bsi, imm, op):
        cols, vals = data
        for q in (0.25, 0.75):
            pred = int(np.quantile(vals, q))
            assert imm.compare(op, pred, pred + 50) == \
                bsi.compare(op, pred, pred + 50), (op, pred)

    def test_sum_topk_getvalue(self, data, bsi, imm):
        cols, vals = data
        assert imm.sum() == bsi.sum()
        assert imm.top_k(137) == bsi.top_k(137)
        for c in cols[:20]:
            assert imm.get_value(int(c)) == bsi.get_value(int(c))
        fs = RoaringBitmap.from_values(cols[::7])
        assert imm.sum(fs) == bsi.sum(fs)
        assert imm.compare(Operation.LE, int(np.median(vals)), 0, fs) == \
            bsi.compare(Operation.LE, int(np.median(vals)), 0, fs)

    def test_minmax_pruned_paths(self, bsi, imm):
        assert imm.compare(Operation.LT, bsi.max_value + 10) == \
            bsi.compare(Operation.LT, bsi.max_value + 10)
        assert imm.compare(Operation.GT, -5).cardinality == \
            bsi.get_existence_bitmap().cardinality

    def test_mutation_rejected(self, imm):
        with pytest.raises(TypeError):
            imm.set_value(1, 2)
        with pytest.raises(TypeError):
            imm.merge(imm)
        with pytest.raises(TypeError):
            imm.run_optimize()

    def test_to_mutable_roundtrip(self, bsi, imm):
        mut = imm.to_mutable()
        assert mut == bsi
        mut.set_value(0xFFFFFF, 7)  # mutable copy mutates fine
        assert mut.get_value(0xFFFFFF) == (7, True)

    def test_mmap_file(self, bsi, tmp_path_factory):
        from roaringbitmap_tpu.bsi import ImmutableBitSliceIndex

        path = tmp_path_factory.mktemp("bsi") / "index.bsi"
        path.write_bytes(bsi.serialize_buffer())
        imm = ImmutableBitSliceIndex.mapped(str(path))
        pred = (bsi.min_value + bsi.max_value) // 2
        assert imm.compare(Operation.GE, pred) == \
            bsi.compare(Operation.GE, pred)
        assert imm.sum() == bsi.sum()

    def test_device_from_immutable(self, data, bsi, imm):
        """mmap -> HBM: DeviceBSI accepts the immutable tier directly —
        full seam parity (compare/cardinality/sum/topK) so it cannot
        silently regress."""
        dev = DeviceBSI(imm)
        pred = int(np.median(data[1]))
        for op in (Operation.LT, Operation.GE):
            want = bsi.compare(op, pred)
            assert dev.compare(op, pred) == want, op
            assert dev.compare_cardinality(op, pred) == want.cardinality, op
        assert dev.sum() == bsi.sum()
        k = min(100, bsi.cardinality)
        assert dev.top_k(k) == bsi.top_k(k)

    def test_truncated_rejected(self, bsi):
        from roaringbitmap_tpu.bsi import ImmutableBitSliceIndex
        from roaringbitmap_tpu.format.spec import InvalidRoaringFormat

        data = bsi.serialize_buffer()
        for cut in (4, 12, len(data) // 2):
            with pytest.raises(InvalidRoaringFormat):
                ImmutableBitSliceIndex(data[:cut])


class TestDeviceBSI:
    @pytest.fixture(scope="class")
    def dev(self, bsi):
        return DeviceBSI(bsi)

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_device_matches_host(self, data, bsi, dev, op):
        cols, vals = data
        pred = int(np.quantile(vals, 0.6))
        host = bsi.o_neil_compare(op, pred)
        device = dev.compare(op, pred)
        assert device == host

    def test_device_range(self, data, bsi, dev):
        cols, vals = data
        a, b = int(np.quantile(vals, 0.3)), int(np.quantile(vals, 0.9))
        host = bsi.compare(Operation.RANGE, a, b)
        assert dev.compare(Operation.RANGE, a, b) == host

    def test_device_found_set(self, data, bsi, dev):
        cols, vals = data
        fs = RoaringBitmap.from_values(cols[::5])
        pred = int(np.median(vals))
        assert dev.compare(Operation.LT, pred, found_set=fs) == \
            bsi.compare(Operation.LT, pred, found_set=fs)

    def test_device_predicate_reuse_no_recompile(self, data, dev, bsi):
        # same compiled executable across predicates: just correctness here
        for q in (0.1, 0.5, 0.9):
            pred = int(np.quantile(data[1], q))
            assert dev.compare(Operation.LE, pred) == \
                bsi.compare(Operation.LE, pred)

    def test_device_sum(self, data, bsi, dev):
        assert dev.sum() == bsi.sum()
        fs = RoaringBitmap.from_values(data[0][:1000])
        assert dev.sum(fs) == bsi.sum(fs)

    def test_device_top_k(self, data, bsi, dev):
        for k in (1, 100, 999):
            assert dev.top_k(k) == bsi.top_k(k)

    def test_device_compare_cardinality(self, data, bsi, dev):
        pred = int(np.median(data[1]))
        assert dev.compare_cardinality(Operation.GT, pred) == \
            bsi.compare(Operation.GT, pred).cardinality

    def test_found_set_with_stray_keys(self, data, bsi, dev):
        """foundSet rows the index never stored: NEQ must keep them
        (oNeilCompare NEQ = foundSet \\ EQ), other ops must drop them."""
        cols, vals = data
        stray = np.array([0xFE000001, 0xFE000002], dtype=np.uint32)
        fs = RoaringBitmap.from_values(np.concatenate([cols[:50], stray]))
        pred = int(np.median(vals))
        for op in ALL_OPS:
            host = bsi.o_neil_compare(op, pred, fs)
            device = dev.compare(op, pred, found_set=fs)
            assert device == host, op
        assert dev.compare_cardinality(Operation.NEQ, pred, found_set=fs) == \
            bsi.o_neil_compare(Operation.NEQ, pred, fs).cardinality

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_device_out_of_range_predicates(self, data, bsi, dev, op):
        """Predicates outside [min,max] — incl. negative and >= 2^31 — must
        hit the shared min/max pruning, not wrap through an int32 cast
        (ADVICE r1: DeviceBSI.compare predicate wrap)."""
        for pred in (-1, -(1 << 35), 0, bsi.max_value + 1, 1 << 31, 1 << 40):
            end = pred + 10
            host = bsi.compare(op, pred, end)
            device = dev.compare(op, pred, end)
            assert device == host, (op, pred)
            assert dev.compare_cardinality(op, pred, end) == host.cardinality

    def test_value_above_int32_rejected(self):
        with pytest.raises(ValueError):
            RoaringBitmapSliceIndex.from_pairs(
                np.array([1], dtype=np.uint32),
                np.array([1 << 31], dtype=np.int64))
        bsi = RoaringBitmapSliceIndex()
        with pytest.raises(ValueError):
            bsi.set_value(1, 1 << 31)


def test_chained_device_probes_parity(rng):
    """The chained-marginal probes (barrier methodology) must agree with the
    one-shot host results: BSI compare, RangeBitmap threshold, pairwise."""
    import jax.numpy as jnp  # noqa: F401
    from roaringbitmap_tpu.bsi.device import DeviceBSI, DeviceRangeBitmap
    from roaringbitmap_tpu.bsi.slice_index import (
        Operation, RoaringBitmapSliceIndex)
    from roaringbitmap_tpu.core.rangebitmap import RangeBitmap
    from roaringbitmap_tpu.parallel import aggregation

    vals = rng.integers(0, 1 << 18, 5000).astype(np.uint64)
    rows = np.arange(vals.size, dtype=np.uint32)
    bsi = RoaringBitmapSliceIndex.from_pairs(rows, vals)
    dev = DeviceBSI(bsi)
    thr = int(np.median(vals))
    want = bsi.compare(Operation.LT, thr, 0, None).cardinality
    got = int(np.asarray(dev.chained_compare_cardinality(
        Operation.LT, thr, 4)()))
    assert got == (4 * want) % 2**32

    app = RangeBitmap.appender(1 << 18)
    app.add_many(vals)
    rb = app.build()
    drb = DeviceRangeBitmap(rb)
    want_r = rb.lte(thr).cardinality
    got_r = int(np.asarray(drb.chained_cardinality("lte", thr, 0, 4)()))
    assert got_r == (4 * want_r) % 2**32

    from roaringbitmap_tpu import RoaringBitmap
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 18, 3000).astype(np.uint32)) for _ in range(6)]
    pairs = list(zip(bms[:-1], bms[1:]))
    for op, host in (("and", lambda a, b: a & b), ("or", lambda a, b: a | b)):
        want_p = sum(host(a, b).cardinality for a, b in pairs)
        for eng in ("xla", "pallas"):
            fn, _ = aggregation.chained_pairwise_cardinality(
                op, pairs, 3, engine=eng)
            assert int(np.asarray(fn())) == (3 * want_p) % 2**32, (op, eng)



def test_chained_sum_topk_between_probes_parity(rng):
    """Round-4 probes: chained sum / topK / single-pass between must agree
    with host one-shots (bit-exact per rep, mod 2^32)."""
    from roaringbitmap_tpu.bsi.device import DeviceBSI, DeviceRangeBitmap
    from roaringbitmap_tpu.bsi.slice_index import RoaringBitmapSliceIndex
    from roaringbitmap_tpu.core.rangebitmap import RangeBitmap

    vals = rng.integers(0, 1 << 18, 4000).astype(np.uint64)
    rows = np.arange(vals.size, dtype=np.uint32)
    bsi = RoaringBitmapSliceIndex.from_pairs(rows, vals)
    dev = DeviceBSI(bsi)

    want_sum = bsi.sum()[0]
    got = int(np.asarray(dev.chained_sum_cardinality(3)()))
    assert got == (3 * want_sum) % 2**32

    k = 777
    pre_trim = int(np.asarray(dev._topk_words(k, dev.ebm)[1]).sum())
    assert pre_trim >= k
    got = int(np.asarray(dev.chained_topk_cardinality(k, 3)()))
    assert got == (3 * pre_trim) % 2**32

    app = RangeBitmap.appender(1 << 18)
    app.add_many(vals)
    rb = app.build()
    drb = DeviceRangeBitmap(rb)
    a, b = int(np.quantile(vals, 0.25)), int(np.quantile(vals, 0.75))
    want_btw = int(((vals >= a) & (vals <= b)).sum())
    assert rb.between(a, b).cardinality == want_btw   # host single-pass
    assert drb.between_cardinality(a, b) == want_btw  # device single-pass
    got = int(np.asarray(drb.chained_cardinality("between", a, b, 3)()))
    assert got == (3 * want_btw) % 2**32


def test_between_single_pass_edges(rng):
    """Double-bound scan edge parity: bounds at/beyond extremes, empty
    window, lo == hi, context given (vs the old gte-AND-lte composition)."""
    from roaringbitmap_tpu.core.rangebitmap import RangeBitmap
    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.core.bitmap import and_ as rb_and

    vals = rng.integers(0, 5000, 3000).astype(np.uint64)
    app = RangeBitmap.appender(5000)
    app.add_many(vals)
    rb = app.build()
    ctx = RoaringBitmap.from_values(
        np.arange(0, vals.size, 3, dtype=np.uint32))
    mx = int(vals.max())
    for lo, hi in [(0, mx), (-5, mx + 10), (17, 17), (200, 100),
                   (0, 0), (mx, mx), (1, mx - 1), (mx + 1, mx + 5)]:
        want = rb_and(rb.gte(lo), rb.lte(hi))
        assert rb.between(lo, hi) == want, (lo, hi)
        got_ctx = rb.between(lo, hi, ctx)
        assert got_ctx == rb_and(want, ctx), (lo, hi)


def test_range_bounds_beyond_bit_count_clamped(rng):
    """RANGE with an end above max_value (beyond bit_count bits) must clamp,
    not silently truncate: values 5..100 (7 bits), RANGE [10, 200] == GE 10."""
    from roaringbitmap_tpu.bsi.device import DeviceBSI
    from roaringbitmap_tpu.parallel.sharding import ShardedBSI
    import jax
    from jax.sharding import Mesh

    vals = np.arange(5, 101, dtype=np.uint64)
    cols = np.arange(vals.size, dtype=np.uint32)
    bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
    want = int((vals >= 10).sum())    # == RANGE [10, 200] truth
    got = bsi.compare(Operation.RANGE, 10, 200)
    assert got.cardinality == want
    dev = DeviceBSI(bsi)
    assert dev.compare(Operation.RANGE, 10, 200) == got
    assert dev.compare_cardinality(Operation.RANGE, 10, 200) == want
    devs = jax.devices()
    if len(devs) >= 8:
        mesh = Mesh(np.array(devs[:8]).reshape(4, 2), ("rows", "lanes"))
        sb = ShardedBSI(mesh, bsi)
        assert sb.compare_cardinality(Operation.RANGE, 10, 200) == want
    # low bound below min_value clamps too
    assert bsi.compare(Operation.RANGE, -50, 40).cardinality == \
        int((vals <= 40).sum())


def test_bsi_reference_naming_surface(rng):
    """The last BSI sweep names: serialize/deserialize canonical pair,
    valueExist spelling, getLongCardinality, and the immutable's
    toMutableBitSliceIndex + mutator guards."""
    from roaringbitmap_tpu.bsi.immutable import ImmutableBitSliceIndex
    from roaringbitmap_tpu.bsi.slice_index import RoaringBitmapSliceIndex

    cols = np.unique(rng.integers(0, 1 << 18, 2000)).astype(np.uint32)
    vals = rng.integers(0, 1 << 12, cols.size).astype(np.uint64)
    bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
    assert RoaringBitmapSliceIndex.deserialize(bsi.serialize()) == bsi
    # canonical form pairs with serialized_size_in_bytes (buffer format)
    assert len(bsi.serialize()) == bsi.serialized_size_in_bytes()
    assert bsi.serialize() == bsi.serialize_buffer()
    # public addDigit ripples carries like add()
    from roaringbitmap_tpu import RoaringBitmap as RB32
    twin = RoaringBitmapSliceIndex.from_pairs(cols, vals)
    twin.add_digit(twin.get_existence_bitmap(), 0)  # +1 to every column
    got = [twin.get_value(int(c))[0] for c in cols[:50]]
    assert got == [int(v) + 1 for v in vals[:50]]
    assert bsi.value_exist(int(cols[0])) and not bsi.value_exist(1 << 30)
    assert bsi.long_cardinality == bsi.cardinality == cols.size
    imm = ImmutableBitSliceIndex(bsi.serialize_buffer())
    assert imm.to_mutable_bit_slice_index() == bsi
    with pytest.raises(TypeError):
        imm.add_digit(0, 1)
