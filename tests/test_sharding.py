"""Multi-device determinism: sharded aggregation equals single-device, for
every mesh factorization — the fake-cluster analog of the reference's
pool-size determinism tests (ParallelAggregationTest.java:26-40)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.ops import packing
from roaringbitmap_tpu.parallel import sharding
from roaringbitmap_tpu.utils import datasets


@pytest.fixture(scope="module")
def workload():
    return datasets.synthetic_bitmaps(16, seed=3, universe=1 << 20, density=0.02)


@pytest.fixture(scope="module")
def mesh8():
    return Mesh(np.array(jax.devices()).reshape(4, 2), ("rows", "lanes"))


MESH_SHAPES = [(8, 1), (4, 2), (2, 4), (1, 8)]


@pytest.fixture(params=MESH_SHAPES, ids=lambda s: f"{s[0]}x{s[1]}")
def mesh(request):
    rows, lanes = request.param
    return Mesh(np.array(jax.devices()).reshape(rows, lanes),
                ("rows", "lanes"))


@pytest.fixture(scope="module")
def oracle_or(workload):
    acc = RoaringBitmap()
    for b in workload:
        acc.ior(b)
    return acc


def test_sharded_or_all_mesh_shapes(workload, oracle_or, mesh):
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, "or", workload,
                                                          fallback=False)
    got = packing.unpack_result(keys, words, cards)
    assert got == oracle_or


def test_sharded_xor_all_mesh_shapes(workload, mesh):
    acc = RoaringBitmap()
    for b in workload:
        acc.ixor(b)
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, "xor", workload,
                                                          fallback=False)
    got = packing.unpack_result(keys, words, cards)
    assert got == acc


def test_ragged_aggregator_rejects_and():
    # the ragged segmented path cannot AND (missing rows would be ignored);
    # wide_aggregate_sharded routes "and" to the workShy two-stage path
    devs = np.array(jax.devices()).reshape(8, 1)
    mesh = Mesh(devs, ("rows", "lanes"))
    with pytest.raises(ValueError):
        sharding.make_sharded_aggregator(mesh, "and", 4, 2)


def test_sharded_and_matches_host(workload, mesh):
    acc = workload[0].clone()
    for b in workload[1:]:
        acc.iand(b)
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, "and", workload,
                                                          fallback=False)
    got = packing.unpack_result(keys, words, cards)
    assert got == acc


def test_sharded_and_nonempty(workload):
    base = RoaringBitmap.from_values(np.arange(0, 300000, 7, dtype=np.uint32))
    bms = [base | b for b in workload[:6]]
    acc = bms[0].clone()
    for b in bms[1:]:
        acc.iand(b)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("rows", "lanes"))
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, "and", bms,
                                                          fallback=False)
    assert packing.unpack_result(keys, words, cards) == acc
    assert acc.cardinality >= base.cardinality


@pytest.mark.parametrize("op", ["or", "xor", "and"])
def test_sharded_census1881_parity(op):
    """Dataset-scale mesh parity (VERDICT r1 item 6)."""
    if not datasets.has_dataset("census1881"):
        pytest.skip("census1881 unavailable")
    bms = datasets.load_bitmaps("census1881")
    if op == "and":
        oracle = bms[0].clone()
        for b in bms[1:]:
            oracle.iand(b)
    else:
        oracle = RoaringBitmap()
        for b in bms:
            (oracle.ior if op == "or" else oracle.ixor)(b)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("rows", "lanes"))
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, op, bms,
                                                          fallback=False)
    assert packing.unpack_result(keys, words, cards) == oracle


def test_compact_ingest_sharded_parity(rng, mesh):
    """ingest="compact" (streams sharded, per-shard device densify) must be
    bit-identical to the host-densified dense ingest — incl. byte-backed
    sources, which ship ~serialized-size to the mesh — on every mesh
    factorization (the shard split changes with the row-axis size)."""
    bms = []
    for i in range(12):
        vals = [rng.integers(0, 1 << 20, 600),
                (2 << 16) + rng.integers(0, 9000, 6000)]
        start = (3 << 16) + int(rng.integers(0, 900))
        vals.append(np.arange(start, start + 5000 + 97 * i))
        b = RoaringBitmap.from_values(np.concatenate(vals).astype(np.uint32))
        b.run_optimize()
        bms.append(b)
    for op in ("or", "xor"):
        kd, wd, cd = sharding.wide_aggregate_sharded(mesh, op, bms, ingest="dense",
                                                    fallback=False)
        for src in (bms, [b.serialize() for b in bms]):
            kc, wc, cc = sharding.wide_aggregate_sharded(mesh, op, src,
                                                         fallback=False,
                                                   ingest="compact")
            got = packing.unpack_result(kc, wc, cc)
            want = packing.unpack_result(kd, wd, cd)
            assert got == want, (op, type(src[0]).__name__)


def test_sharded_ingest_validation_and_bytes_and(mesh8, rng):
    bms = [RoaringBitmap.from_values(
        np.concatenate([np.arange(5, 400),
                        ((i + 1) << 16) + rng.integers(0, 5000, 100)])
        .astype(np.uint32)) for i in range(4)]
    with pytest.raises(ValueError, match="unknown ingest"):
        sharding.wide_aggregate_sharded(mesh8, "or", bms, ingest="streams")
    # AND over raw bytes: zero-copy wrap, workShy path, exact result
    want = bms[0] & bms[1] & bms[2] & bms[3]
    assert want.cardinality
    keys, words, cards = sharding.wide_aggregate_sharded(
        mesh8, "and", [b.serialize() for b in bms], ingest="compact",
        fallback=False)
    assert packing.unpack_result(keys, words, cards) == want


def test_dense_ingest_accepts_bytes(mesh8, rng):
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 18, 2000).astype(np.uint32)) for _ in range(6)]
    want = RoaringBitmap()
    for b in bms:
        want.ior(b)
    keys, words, cards = sharding.wide_aggregate_sharded(
        mesh8, "or", [b.serialize() for b in bms], ingest="dense",
        fallback=False)
    assert packing.unpack_result(keys, words, cards) == want


def test_sharded_bsi_parity(mesh8):
    """ShardedBSI.compare/sum over the 8-device mesh == host BSI (VERDICT
    r3 #9: slice axis replicated, key axis sharded)."""
    from roaringbitmap_tpu.bsi.slice_index import (
        Operation, RoaringBitmapSliceIndex)
    from roaringbitmap_tpu.parallel.sharding import ShardedBSI

    rng = np.random.default_rng(17)
    # span several containers so the key axis actually shards
    cols = np.unique(rng.integers(0, 1 << 20, 6000)).astype(np.uint32)
    vals = rng.integers(0, 1 << 16, cols.size).astype(np.uint64)
    bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
    sb = ShardedBSI(mesh8, bsi)
    thr = int(np.median(vals))
    for op in (Operation.LT, Operation.GE, Operation.EQ, Operation.NEQ):
        want = bsi.compare(op, thr, 0, None).cardinality
        assert sb.compare_cardinality(op, thr) == want, op
    a, b = int(np.quantile(vals, 0.2)), int(np.quantile(vals, 0.8))
    want = bsi.compare(Operation.RANGE, a, b, None).cardinality
    assert sb.compare_cardinality(Operation.RANGE, a, b) == want
    # out-of-range predicates ride the min/max pruning
    assert sb.compare_cardinality(Operation.LT, -5) == 0
    assert sb.compare_cardinality(
        Operation.LE, 1 << 40) == bsi.ebm.cardinality
    assert sb.sum() == bsi.sum()


def test_sharded_64bit_tier(mesh8):
    """Roaring64Bitmap rides the same sharded wide ops: the segment axis
    is the u64 high-48 key instead of the u16 key (SURVEY §2.3), and
    unpack_result restores the 64-bit class from the key dtype."""
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    rng = np.random.default_rng(5)
    bms = [Roaring64Bitmap.from_values(
        rng.integers(0, 1 << 40, 5000, dtype=np.uint64)) for _ in range(8)]
    oracles = {"or": Roaring64Bitmap(), "xor": Roaring64Bitmap(),
               "and": bms[0].clone()}
    for b in bms:
        oracles["or"].ior(b)
        oracles["xor"].ixor(b)
    for b in bms[1:]:
        oracles["and"].iand(b)
    for op in ("or", "xor", "and"):
        keys, words, cards = sharding.wide_aggregate_sharded(mesh8, op, bms,
                                                        fallback=False)
        got = packing.unpack_result(keys, words, cards)
        assert isinstance(got, Roaring64Bitmap)
        assert got == oracles[op], op


def test_sharded_bsi_topk(mesh8):
    """ShardedBSI.top_k_cardinality == DeviceBSI's pre-trim candidate
    cardinality, and >= k whenever k rows exist."""
    from roaringbitmap_tpu.bsi.device import DeviceBSI
    from roaringbitmap_tpu.bsi.slice_index import RoaringBitmapSliceIndex
    from roaringbitmap_tpu.parallel.sharding import ShardedBSI

    rng = np.random.default_rng(23)
    cols = np.unique(rng.integers(0, 1 << 19, 4000)).astype(np.uint32)
    vals = rng.integers(0, 1 << 12, cols.size).astype(np.uint64)
    bsi = RoaringBitmapSliceIndex.from_pairs(cols, vals)
    sb = ShardedBSI(mesh8, bsi)
    db = DeviceBSI(bsi)
    for k in (1, 50, cols.size // 2, cols.size):
        want = int(np.asarray(db._topk_words(k, db.ebm)[1]).sum())
        got = sb.top_k_cardinality(k)
        assert got == want, k
        assert got >= k


def test_sharded_rangebitmap_parity(mesh8):
    """ShardedRangeBitmap threshold/between cardinalities == host
    RangeBitmap over the 8-device mesh (VERDICT r3 missing #5)."""
    from roaringbitmap_tpu.core.rangebitmap import RangeBitmap
    from roaringbitmap_tpu.parallel.sharding import ShardedRangeBitmap

    rng = np.random.default_rng(29)
    vals = rng.integers(0, 100_000, 80_000).astype(np.uint64)
    app = RangeBitmap.appender(int(vals.max()))
    app.add_many(vals)
    rbm = app.build()
    srb = ShardedRangeBitmap(mesh8, rbm)
    thr = int(np.median(vals))
    lo, hi = int(np.percentile(vals, 25)), int(np.percentile(vals, 75))
    assert srb.lte_cardinality(thr) == rbm.lte(thr).cardinality
    assert srb.lt_cardinality(thr) == rbm.lt(thr).cardinality
    assert srb.gte_cardinality(thr) == rbm.gte(thr).cardinality
    assert srb.gt_cardinality(thr) == rbm.gt(thr).cardinality
    assert srb.eq_cardinality(thr) == rbm.eq(thr).cardinality
    assert srb.neq_cardinality(thr) == rbm.neq(thr).cardinality
    assert (srb.between_cardinality(lo, hi)
            == rbm.between(lo, hi).cardinality)
    # boundary guards match the host semantics
    assert srb.lte_cardinality(-1) == 0
    assert srb.gte_cardinality(0) == srb.rows
    assert srb.between_cardinality(hi, lo) == 0
    assert srb.between_cardinality(-5, 1 << 40) == srb.rows


def test_sharded_key_budget_guard(mesh8):
    """make_sharded_aggregator refuses K beyond the per-device accumulator
    ceiling with a typed error (VERDICT r4 weak #5)."""
    with pytest.raises(sharding.ShardedKeyBudgetError, match="ceiling"):
        sharding.make_sharded_aggregator(
            mesh8, "or", sharding.MAX_KEYS_PER_SHARD_PASS + 1, 2)


@pytest.mark.parametrize("ingest", ["dense", "compact"])
def test_sharded_chunked_wide_keyspace(mesh8, ingest):
    """A >2^13-key workload aggregates correctly through the key-chunked
    path, proving per-device memory stays under the ceiling for any K (the
    compiled accumulator is (chunk_K+1) x 8 KiB; a larger K would raise
    ShardedKeyBudgetError instead of allocating)."""
    n_keys = 2 * sharding.MAX_KEYS_PER_SHARD_PASS + 777
    base = np.arange(n_keys, dtype=np.uint32) << 16
    bms = [RoaringBitmap.from_values(base + np.uint32(7 * i))
           for i in range(4)]
    # overlap so the reduce is non-trivial + a dense container mid-range
    bms.append(RoaringBitmap.from_values(
        (1000 << 16) + np.arange(30000, dtype=np.uint32)))
    for op in ("or", "xor"):
        oracle = RoaringBitmap()
        for b in bms:
            (oracle.ior if op == "or" else oracle.ixor)(b)
        keys, words, cards = sharding.wide_aggregate_sharded(
            mesh8, op, bms, ingest=ingest, fallback=False)
        assert keys.size == n_keys
        got = packing.unpack_result(keys, words, cards)
        assert got == oracle, op
    # the ceiling constant must still equal the documented 32 MiB budget
    # (8 KiB per key row), independent recomputation not a tautology
    assert sharding.MAX_KEYS_PER_SHARD_PASS * 8192 == 32 << 20


def test_global_mesh_single_host(workload, oracle_or):
    """multihost.global_mesh degenerates to the local mesh on one host and
    feeds the sharded engine unchanged — the same program text scales to a
    pod by changing only the launcher."""
    from roaringbitmap_tpu.parallel import multihost

    mesh = multihost.global_mesh()
    assert mesh.devices.size == len(jax.devices())
    r, l = mesh.devices.shape
    assert r * l == 8 and r & (r - 1) == 0
    # single host: every device is local, so the butterfly row axis takes
    # them all and consecutive devices are row-adjacent
    assert (r, l) == (8, 1)
    assert [d.id for d in mesh.devices[:, 0]] == sorted(
        d.id for d in jax.devices())
    keys, words, cards = sharding.wide_aggregate_sharded(mesh, "or", workload,
                                                          fallback=False)
    assert packing.unpack_result(keys, words, cards) == oracle_or
    # explicit lane counts, incl. every valid factorization
    for lanes in (1, 2, 4, 8):
        m = multihost.global_mesh(lanes=lanes)
        assert m.devices.shape == (8 // lanes, lanes)
    with pytest.raises(ValueError, match="does not divide"):
        multihost.global_mesh(lanes=3)


def test_global_mesh_groups_by_process():
    """Multi-host placement (pure _arrange): row columns are host-pure
    even when the global device order interleaves hosts, and the default
    row length divides every process's local count."""
    from roaringbitmap_tpu.parallel import multihost

    class Dev:
        def __init__(self, i, p):
            self.id, self.process_index = i, p

        def __repr__(self):
            return f"d{self.id}@p{self.process_index}"

    # 2 hosts x 6 devices, ids interleaved across hosts
    devs = [Dev(i, i % 2) for i in range(12)]
    arr = multihost._arrange(devs, lanes=None)
    rows, lanes = arr.shape
    assert rows == 2 and lanes == 6  # pow2 floor dividing local count 6
    for j in range(lanes):  # every column single-process
        assert len({d.process_index for d in arr[:, j]}) == 1
    # both hosts contribute whole columns
    procs = [arr[0, j].process_index for j in range(lanes)]
    assert procs == [0, 0, 0, 1, 1, 1]
    # all 12 devices placed exactly once
    assert sorted(d.id for d in arr.ravel()) == list(range(12))
    # explicit lanes that force cross-host rows still place every device
    arr2 = multihost._arrange(devs, lanes=3)
    assert arr2.shape == (4, 3)
    assert sorted(d.id for d in arr2.ravel()) == list(range(12))
