"""Pod replay harness acceptance (ISSUE 20).

Pins:
- the seeded generator is deterministic (same profile -> identical
  datasets AND identical event streams, the property cross-process
  parity rests on) and actually mixed (flat + expression + analytics +
  delta events, Zipf-skewed tenants, nondecreasing diurnal arrivals);
- the in-process arm runs on the fault clock with ``replay_stream``
  semantics: full attainment under easy deadlines, typed-only outcomes
  and shed/rejected accounting under an overload ladder;
- ``sustained`` picks the highest ladder rung clearing the SLO target;
- group-commit durability (``FlushPolicy(mode="group")``): one fsync
  covers many tenants' appends (``rb_journal_group_commits_total``),
  fsyncs per applied delta drop vs ``always``, and a crash between
  group members recovers bit-exactly at every armed crash point.
"""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.mutation import delta as mut_delta
from roaringbitmap_tpu.mutation.durability import (DurableTenant,
                                                   FlushPolicy,
                                                   GroupCommitScheduler,
                                                   recover_tenant)
from roaringbitmap_tpu.parallel import expr
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
from roaringbitmap_tpu.parallel.multiset import MultiSetBatchEngine
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                       replay)

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)

PROFILE = replay.ReplayProfile(sets=2, sources=6, tenants=6,
                               density=500, users=1 << 16,
                               requests=80, duration_s=1.0, seed=21)


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    faults.reset_clock()
    yield
    obs.disable()
    obs.reset()
    faults.reset_clock()


def _loop(profile=PROFILE, **kw):
    bitmap_sets, columns = replay.build_dataset(profile)
    sets = [DeviceBitmapSet(b, layout="dense") for b in bitmap_sets]
    replay.attach_columns(sets, profile, columns)
    kw.setdefault("pool_target", 4)
    kw.setdefault("guard", NOSLEEP)
    kw.setdefault("default_deadline_ms", 300_000.0)
    return ServingLoop(MultiSetBatchEngine(sets), ServingPolicy(**kw))


# ------------------------------------------------------------- generator

def test_dataset_and_stream_deterministic():
    """Two independent builds from one profile agree bit for bit — the
    foundation of cross-process parity without shipping data."""
    a_sets, a_cols = replay.build_dataset(PROFILE)
    b_sets, b_cols = replay.build_dataset(PROFILE)
    for sa, sb in zip(a_sets, b_sets):
        for x, y in zip(sa, sb):
            assert np.array_equal(x.to_array(), y.to_array())
    for ca, cb in zip(a_cols, b_cols):
        assert np.array_equal(ca[0], cb[0])
        assert np.array_equal(ca[1], cb[1])
    from roaringbitmap_tpu.wire import protocol as wp

    ev_a, ev_b = replay.generate(PROFILE), replay.generate(PROFILE)
    assert len(ev_a) == len(ev_b) == PROFILE.requests
    for ea, eb in zip(ev_a, ev_b):
        assert ea[0] == eb[0] and ea[1] == eb[1]
        if ea[0] == "query":
            # the wire codec is the canonical form (AdHoc leaves have
            # no stable repr): identical header + identical blob bytes
            assert wp.encode_query(ea[2].query) \
                == wp.encode_query(eb[2].query)
            assert ea[2].tenant == eb[2].tenant


def test_stream_is_mixed_skewed_and_ordered():
    profile = replay.ReplayProfile(sets=2, sources=6, tenants=8,
                                   density=500, users=1 << 16,
                                   requests=400, duration_s=4.0,
                                   zipf_alpha=1.3, seed=3)
    events = replay.generate(profile)
    times = [e[1] for e in events]
    assert times == sorted(times)             # nondecreasing arrivals
    kinds = {"flat": 0, "expression": 0, "analytics": 0, "delta": 0}
    per_tenant: dict = {}
    for e in events:
        if e[0] == "delta":
            kinds["delta"] += 1
            continue
        q = e[2].query
        if isinstance(q, expr.ExprQuery):
            kinds["analytics" if expr.is_agg(q.expr)
                  or _has_pred(q.expr) else "expression"] += 1
        else:
            kinds["flat"] += 1
        per_tenant[e[2].tenant] = per_tenant.get(e[2].tenant, 0) + 1
    assert all(v > 0 for v in kinds.values()), kinds
    counts = sorted(per_tenant.values(), reverse=True)
    assert counts[0] >= 3 * counts[-1]        # Zipf skew is real


def _has_pred(e):
    if isinstance(e, expr.ValuePred):
        return True
    if isinstance(e, expr.Agg):
        return True
    if isinstance(e, expr.Node):
        return any(_has_pred(c) for c in e.children)
    return False


# ---------------------------------------------------------- in-process arm

def test_run_inproc_full_attainment_under_easy_deadline():
    loop = _loop()
    rep = replay.run_inproc(loop, replay.generate(PROFILE))
    assert rep["queries"] + rep["deltas"] == PROFILE.requests
    assert rep["done"] == rep["queries"]
    assert rep["attainment"] == 1.0
    assert rep["typed_only"]
    assert rep["p99_ms"] >= rep["p50_ms"] >= 0.0


def test_run_inproc_overload_is_typed_and_accounted():
    """A tight deadline + compressed arrivals: sheds and rejections
    appear, every one typed, and the counts reconcile exactly."""
    profile = replay.ReplayProfile(
        sets=2, sources=6, tenants=6, density=500, users=1 << 16,
        requests=60, duration_s=0.5, deadline_ms=1.0, seed=21)
    loop = _loop(profile, max_queue=4)
    rep = replay.run_inproc(loop, replay.generate(profile),
                            rate_scale=50.0)
    assert rep["typed_only"], rep
    assert (rep["done"] + rep["shed"] + rep["failed"]
            + rep["rejected"]) == rep["queries"]
    assert rep["shed"] + rep["rejected"] > 0, rep
    assert rep["attainment"] < 1.0


def test_sustained_picks_highest_clearing_rung():
    reports = {1.0: {"qps": 100.0, "attainment": 0.99, "p99_ms": 5.0,
                     "typed_only": True},
               2.0: {"qps": 180.0, "attainment": 0.93, "p99_ms": 9.0,
                     "typed_only": True},
               4.0: {"qps": 200.0, "attainment": 0.55, "p99_ms": 40.0,
                     "typed_only": True}}

    def run_one(rate):
        r = dict(reports[rate])
        r.update(queries=1, deltas=0, done=1, shed=0, failed=0,
                 rejected=0, p50_ms=1.0, wall_s=1.0)
        return r

    out = replay.sustained(run_one, [1.0, 2.0, 4.0], slo_target=0.9)
    assert out["sustained_rate_x"] == 2.0
    assert out["sustained_qps"] == 180.0
    assert len(out["ladder"]) == 3


# ----------------------------------------------------------- group commit

def _mk_ds(seed):
    rng = np.random.default_rng(seed)
    return DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 14, 300).astype(np.uint32)))
        for _ in range(3)], layout="dense")


def _counter_total(name):
    return sum(r["value"]
               for r in obs.snapshot()["counters"].get(name, []))


def test_group_commit_amortizes_fsyncs(tmp_path):
    """One scheduler, 4 tenants: the fsyncs-per-applied-delta ratio
    must come in strictly below ``always`` (1.0), and the group-commit
    counter must tick."""
    sched = GroupCommitScheduler(every_n=8)
    tenants = [DurableTenant(_mk_ds(40 + i), root=str(tmp_path),
                             tenant=f"t{i}", policy=sched.policy())
               for i in range(4)]
    f0 = _counter_total("rb_journal_fsyncs_total")
    applies = 0
    for k in range(6):
        for t in tenants:
            t.apply_delta(adds={k % 3: np.array([60000 + k], np.uint32)})
            applies += 1
    sched.commit()                            # shutdown barrier
    fsyncs = _counter_total("rb_journal_fsyncs_total") - f0
    commits = _counter_total("rb_journal_group_commits_total")
    assert commits >= 2
    assert fsyncs < applies, (fsyncs, applies)
    assert sched.stats["appends"] == applies
    ref = [[bm.serialize() for bm in mut_delta.host_bitmaps(t.ds)]
           for t in tenants]
    for t in tenants:
        t.close()
    for i in range(4):
        rec, _ = recover_tenant(root=str(tmp_path), tenant=f"t{i}",
                                policy=FlushPolicy(mode="never"))
        got = [bm.serialize() for bm in mut_delta.host_bitmaps(rec.ds)]
        assert got == ref[i], f"t{i} lost a group-buffered record"
        rec.close()


@pytest.mark.parametrize("point", ["pre_append", "pre_apply", "torn",
                                   "post_apply"])
def test_group_commit_crash_between_members_bit_exact(tmp_path, point):
    """Crash while one group member is mid-append: BOTH tenants recover
    bit-exactly vs never-crashed host oracles — the un-acked record is
    lost or kept exactly as its own journal says, never cross-tenant."""
    root = str(tmp_path / point)
    sched = GroupCommitScheduler(every_n=3)
    tenants = [DurableTenant(_mk_ds(70 + i), root=root, tenant=f"g{i}",
                             policy=sched.policy()) for i in range(2)]
    oracles = [_oracle(70 + i) for i in range(2)]
    rng = np.random.default_rng(9)

    def step(k):
        return {int(rng.integers(3)):
                np.unique(rng.integers(0, 1 << 14, 12)).astype(
                    np.uint32)}

    k = 0
    crashed_i = None
    with faults.inject(f"crash@{point}=0.25:5"):
        try:
            for k in range(10):
                for i, t in enumerate(tenants):
                    crashed_i = i
                    adds = step(k)
                    t.apply_delta(adds=adds)
                    _oracle_apply(oracles[i], adds)
        except errors.InjectedCrash:
            pass
        else:
            pytest.skip(f"crash@{point} never fired in 20 applies")
    committed = point in ("pre_apply", "post_apply")
    if committed:
        # the crashing tenant's record IS durable: oracle keeps it
        _oracle_apply(oracles[crashed_i], adds)
    for t in tenants:
        t.journal.close()
    for i in range(2):
        rec, report = recover_tenant(root=root, tenant=f"g{i}",
                                     policy=FlushPolicy(mode="never"))
        got = [bm.serialize() for bm in mut_delta.host_bitmaps(rec.ds)]
        want = [bm.serialize() for bm in oracles[i]]
        assert got == want, (f"tenant g{i} diverged after crash at "
                             f"{point} (crashing member: g{crashed_i})")
        if i == crashed_i:
            assert report["torn"] == (point == "torn")
        rec.close()


def _oracle(seed):
    rng = np.random.default_rng(seed)
    return [RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 14, 300).astype(np.uint32)))
        for _ in range(3)]


def _oracle_apply(hosts, adds):
    for src, vs in adds.items():
        a = RoaringBitmap()
        a.add_many(np.asarray(vs, np.uint32))
        hosts[src] = hosts[src] | a


def test_group_policy_validation():
    with pytest.raises(ValueError):
        FlushPolicy(mode="group")             # no scheduler handle
    with pytest.raises(ValueError):
        FlushPolicy(mode="group", every_n=0,
                    group=GroupCommitScheduler())
    with pytest.raises(ValueError):
        GroupCommitScheduler(every_n=0)
    p = GroupCommitScheduler(every_n=5).policy()
    assert p.mode == "group" and p.every_n == 5
