"""Device-layout recommendation (insights HBM accounting tier)."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap


def test_recommend_device_layout():
    from roaringbitmap_tpu.insights.analysis import recommend_device_layout

    dense_set = [RoaringBitmap.from_values(
        np.arange(0, 60000, 2, dtype=np.uint32)) for _ in range(4)]
    rec = recommend_device_layout(dense_set)
    assert rec["layout"] == "dense" and rec["dense_blowup"] < 4
    sparse_set = [RoaringBitmap.bitmap_of(i << 16) for i in range(30)]  # 8 KB rows for 1-bit containers
    rec2 = recommend_device_layout(sparse_set)
    assert rec2["layout"] == "compact" and rec2["dense_blowup"] >= 32
    # budget pressure flips dense sets to compact too
    rec3 = recommend_device_layout(dense_set, hbm_budget_bytes=16 << 10)
    assert rec3["layout"] == "compact"
