"""Device-layout recommendation (insights HBM accounting tier)."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap


def test_recommend_device_layout():
    from roaringbitmap_tpu.insights.analysis import recommend_device_layout

    dense_set = [RoaringBitmap.from_values(
        np.arange(0, 60000, 2, dtype=np.uint32)) for _ in range(4)]
    rec = recommend_device_layout(dense_set)
    assert rec["layout"] == "dense" and rec["dense_blowup"] < 4
    # extreme blowup alone no longer forces compact — but the mostly-
    # singleton inflation shape is advised counts, matching the
    # DeviceBitmapSet layout="auto" build default (choose_layout, the
    # uscensus2000 cliff shape) so the two advisers never contradict
    sparse_set = [RoaringBitmap.bitmap_of(i << 16) for i in range(30)]  # 8 KB rows for 1-bit containers
    rec2 = recommend_device_layout(sparse_set)
    assert rec2["layout"] == "counts" and rec2["dense_blowup"] >= 32
    # budget overflow walks the ladder down to compact
    rec3 = recommend_device_layout(dense_set, hbm_budget_bytes=16 << 10)
    assert rec3["layout"] == "compact"
    # bitmap-heavy set where counts cannot help (counts_b > dense_b): a
    # budget between dense and counts must NOT skip to compact
    rec3b = recommend_device_layout(
        dense_set, hbm_budget_bytes=rec["dense_hbm_bytes"])
    assert rec3b["layout"] == "dense"
    # array-container set (serialized << dense, blowup < 32): a budget
    # between the counts and dense footprints picks the middle rung
    arr_set = [RoaringBitmap.from_values(
        np.arange(0, 60000, 64, dtype=np.uint32)) for _ in range(4)]
    rec4 = recommend_device_layout(arr_set)
    assert rec4["counts_hbm_bytes"] < rec4["dense_hbm_bytes"]
    budget = (rec4["counts_hbm_bytes"] + rec4["dense_hbm_bytes"]) // 2
    rec5 = recommend_device_layout(arr_set, hbm_budget_bytes=budget)
    assert rec5["layout"] == "counts"
