"""Buffer tier tests — ImmutableRoaringBitmap over bytes and mmap
(the reference's buffer/ suite incl. TestMemoryMapping), algebra producing
in-RAM results, and BufferFastAggregation-style wide ops on immutable
inputs."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap, MutableRoaringBitmap
from roaringbitmap_tpu.parallel import aggregation

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"


@pytest.fixture(scope="module")
def sample(rng):
    vals = rng.integers(0, 1 << 24, 40000, dtype=np.uint32)
    rb = RoaringBitmap.from_values(vals)
    rb.run_optimize()
    return rb


@pytest.fixture(scope="module")
def imm(sample):
    return ImmutableRoaringBitmap(sample.serialize())


class TestImmutable:
    def test_header_only_accessors(self, sample, imm):
        assert imm.cardinality == sample.cardinality
        assert not imm.is_empty()
        assert imm.has_run_compression() == sample.has_run_compression()
        assert imm.serialized_size_in_bytes() == sample.serialized_size_in_bytes()

    def test_point_ops(self, sample, imm):
        arr = sample.to_array()
        for x in arr[::5000]:
            assert int(x) in imm
            assert imm.rank(int(x)) == sample.rank(int(x))
        assert imm.first() == sample.first()
        assert imm.last() == sample.last()
        for j in range(0, sample.cardinality, 7001):
            assert imm.select(j) == sample.select(j)

    def test_lazy_container_cache(self, imm, sample):
        fresh = ImmutableRoaringBitmap(sample.serialize())
        assert len(fresh._cache) == 0
        fresh.contains(int(sample.first()))
        assert len(fresh._cache) == 1  # only the touched container parsed

    def test_algebra_returns_inram(self, sample, imm, rng):
        other = RoaringBitmap.from_values(
            rng.integers(0, 1 << 24, 10000, dtype=np.uint32))
        for res, ref in [
            (imm & other, sample & other),
            (imm | other, sample | other),
            (imm ^ other, sample ^ other),
            (imm - other, sample - other),
        ]:
            assert isinstance(res, RoaringBitmap)
            assert res == ref
        # immutable ∘ immutable too
        o_imm = ImmutableRoaringBitmap(other.serialize())
        assert (imm & o_imm) == (sample & other)

    def test_serialize_verbatim(self, sample, imm):
        assert imm.serialize() == sample.serialize()

    def test_roundtrip_and_conversion(self, sample, imm):
        assert imm.to_bitmap() == sample
        m = imm.to_mutable()
        assert isinstance(m, MutableRoaringBitmap)
        m.add(0xFEEDBEEF)
        assert 0xFEEDBEEF in m and 0xFEEDBEEF not in imm
        assert m.to_immutable().cardinality == sample.cardinality + 1

    def test_mutable_copy_does_not_alias(self, sample, imm):
        """to_mutable/to_bitmap must not share the cached container list:
        point mutations on the copy rebind list entries."""
        snapshot = imm.to_bitmap().to_array()
        m = imm.to_mutable()
        m.add(0xFEEDBEEF)
        m.remove(int(snapshot[0]))
        assert np.array_equal(imm.to_bitmap().to_array(), snapshot)

    def test_view_into_larger_frame(self, sample):
        """An embedded bitmap mid-buffer, like ByteBuffer slices."""
        blob = b"\xAA" * 37 + sample.serialize() + b"\xBB" * 11
        imm = ImmutableRoaringBitmap(memoryview(blob)[37:])
        assert imm.cardinality == sample.cardinality
        assert imm.to_bitmap() == sample

    def test_mmap_file(self, sample, tmp_path):
        """Real memory-mapped file (TestMemoryMapping.java analog)."""
        path = os.path.join(tmp_path, "bitmap.bin")
        with open(path, "wb") as f:
            f.write(sample.serialize())
        imm = ImmutableRoaringBitmap.mapped(path)
        assert imm.cardinality == sample.cardinality
        assert imm.first() == sample.first()
        assert (imm & sample) == sample
        assert imm.to_bitmap() == sample

    @pytest.mark.skipif(not os.path.isdir(TESTDATA),
                        reason="reference corpus not mounted")
    @pytest.mark.parametrize("name,card", [("bitmapwithruns.bin", 200100),
                                           ("bitmapwithoutruns.bin", 200100)])
    def test_reference_fixture(self, name, card):
        with open(os.path.join(TESTDATA, name), "rb") as f:
            data = f.read()
        imm = ImmutableRoaringBitmap(data)
        assert imm.cardinality == card
        assert imm.serialize() == data


class TestBufferWideAggregation:
    """BufferFastAggregation analog: wide device ops straight off
    immutable (serialized) inputs."""

    def test_wide_or_on_immutables(self, rng):
        arrs = [rng.integers(0, 1 << 20, 5000, dtype=np.uint32)
                for _ in range(16)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        got = aggregation.or_(imms, engine="xla")
        oracle = np.unique(np.concatenate(arrs))
        assert np.array_equal(got.to_array(), oracle)

    def test_wide_and_on_immutables(self, rng):
        base = np.unique(rng.integers(0, 1 << 18, 3000, dtype=np.uint32))
        arrs = [np.union1d(base, rng.integers(0, 1 << 18, 500, dtype=np.uint32))
                for _ in range(6)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        got = aggregation.and_(imms)
        oracle = arrs[0]
        for a in arrs[1:]:
            oracle = np.intersect1d(oracle, a)
        assert np.array_equal(got.to_array(), oracle)

    def test_device_set_from_immutables(self, rng):
        arrs = [rng.integers(0, 1 << 20, 4000, dtype=np.uint32)
                for _ in range(8)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        ds = aggregation.DeviceBitmapSet(imms)
        got = ds.aggregate("or", engine="xla")
        assert np.array_equal(got.to_array(), np.unique(np.concatenate(arrs)))


class TestLazyBufferTier:
    """Round-4 laziness guarantees: algebra and walks over an
    ImmutableRoaringBitmap decode only the containers they touch, and each
    decode is a zero-copy read-only view into the backing buffer on
    little-endian hosts (buffer/ImmutableRoaringArray.java:166 semantics)."""

    @staticmethod
    def _wide_imm(n_keys: int) -> tuple[RoaringBitmap, "ImmutableRoaringBitmap"]:
        # n_keys containers of mixed kinds
        parts = [np.arange(0, 5000, 1 + (k % 3), dtype=np.uint32) + (k << 16)
                 for k in range(n_keys)]
        rb = RoaringBitmap.from_values(np.concatenate(parts))
        return rb, ImmutableRoaringBitmap(rb.serialize())

    def test_and_decodes_o1_containers(self):
        """AND of a 1-container bitmap against a 10^4-container mapped file
        decodes O(1) containers (VERDICT r3 missing #1 done-criterion)."""
        rb, im = self._wide_imm(10_000)
        probe = RoaringBitmap.from_values(
            (7 << 16) + np.arange(0, 5000, 7, dtype=np.uint32))
        got = im & probe
        want = rb & probe
        assert got == want and got.cardinality
        assert len(im._cache) == 1          # only key 7 decoded

    def test_andnot_decodes_only_intersection_of_rhs(self):
        rb, im = self._wide_imm(64)
        probe = RoaringBitmap.from_values(
            (3 << 16) + np.arange(100, dtype=np.uint32))
        # im as LHS of andnot decodes all of im (result needs it) but a
        # probe-side immutable decodes only the intersecting key
        im_probe = ImmutableRoaringBitmap(probe.serialize())
        got = rb.__sub__(probe)  # host oracle
        from roaringbitmap_tpu.core.bitmap import andnot
        assert andnot(rb, im_probe) == got
        assert len(im_probe._cache) == 1

    def test_iterator_and_range_walks_decode_lazily(self):
        rb, im = self._wide_imm(100)
        # advance_if_needed jumps straight to key 90: earlier containers
        # are never decoded
        it = im.get_int_iterator()
        it.advance_if_needed(90 << 16)
        assert it.next() == (90 << 16)
        assert len(im._cache) <= 3
        # range walk touches only the spanned containers
        im2 = ImmutableRoaringBitmap(rb.serialize())
        seen = []
        im2.for_each_in_range(50 << 16, (50 << 16) + 10, seen.append)
        assert seen == [v for v in rb.to_array()
                        if (50 << 16) <= v < (50 << 16) + 10]
        assert len(im2._cache) <= 4

    def test_rank_iterator_skips_without_decoding(self):
        _, im = self._wide_imm(50)
        it = im.get_int_iterator()  # smoke: full walk still correct
        assert it.has_next()
        from roaringbitmap_tpu.core.iterators import PeekableIntRankIterator
        rit = PeekableIntRankIterator(im)
        rit.advance_if_needed(40 << 16)
        # next value is (40 << 16) itself; rank() already counts it (<= x)
        assert rit.peek_next_rank() == im.rank(40 << 16)
        assert len(im._cache) <= 4          # skipped containers: header only

    def test_zero_copy_views_little_endian(self):
        import sys
        if sys.byteorder != "little":
            pytest.skip("zero-copy only on little-endian hosts")
        rb = RoaringBitmap.from_values(np.concatenate([
            np.arange(100, dtype=np.uint32),                 # array
            (1 << 16) + np.arange(5000, dtype=np.uint32),    # bitmap
        ]).astype(np.uint32))
        rb.run_optimize()
        blob = rb.serialize()
        im = ImmutableRoaringBitmap(blob)
        src = np.frombuffer(blob, dtype=np.uint8)
        for i in range(len(im.containers)):
            c = im.containers[i]
            payload = (c.runs if hasattr(c, "runs") else
                       c.words() if c.is_bitmap() else c.values())
            assert np.shares_memory(payload, src), f"container {i} copied"
            assert not payload.flags.writeable
        # read-only backing must not break functional mutation of results
        out = im.to_bitmap()
        out.add(12345)
        assert out.contains(12345) and not im.contains(12345)


@pytest.mark.parametrize("name", [f"crashproneinput{i}.bin"
                                  for i in range(1, 9)])
def test_buffer_adversarial_inputs(name):
    """TestBufferAdversarialInputs.java: the zero-copy buffer tier must
    reject every crash-prone corpus input with InvalidRoaringFormat — at
    wrap or at first decode — never a crash or silent misparse."""
    from roaringbitmap_tpu.format.spec import InvalidRoaringFormat

    path = os.path.join(TESTDATA, name)
    if not os.path.exists(path):
        pytest.skip("reference corpus not mounted")
    with open(path, "rb") as f:
        raw = f.read()
    # the format-level twin lives in test_format.py; this pins the NEW
    # surface — error propagation through the lazy container sequence
    with pytest.raises(InvalidRoaringFormat):
        b = ImmutableRoaringBitmap(raw)
        for _ in b.containers:  # force the lazy decode of every slot
            pass


def test_buffer_naming_aliases_and_pointer(sample, imm):
    """The reference-named conversion/expert surface on the buffer tier:
    toRoaringBitmap / toMutableRoaringBitmap / toImmutableRoaringBitmap,
    isHammingSimilar, andNot(other) in-place, and a container pointer that
    decodes lazily as it advances."""
    rb = imm.to_roaring_bitmap()
    assert rb == imm.to_bitmap()
    mut = imm.to_mutable_roaring_bitmap()
    assert isinstance(mut, MutableRoaringBitmap) and mut == rb
    assert mut.to_immutable_roaring_bitmap().serialize() == imm.serialize()
    assert imm.is_hamming_similar(imm, 0)
    tweak = mut.to_immutable()
    mut.add(4242424242)
    assert imm.is_hamming_similar(mut, 1)
    ptr = imm.get_container_pointer()
    total = 0
    while ptr.has_container():
        total += ptr.get_cardinality()
        ptr.advance()
    assert total == imm.cardinality
    m2 = imm.to_mutable()
    m2.and_not(rb)
    assert m2.is_empty()
    assert tweak == rb


def test_buffer_static_builders(sample, imm):
    """bitmapOf / static range-remove on the buffer classes; the mutable
    class keeps its inherited point remove(x)."""
    m = ImmutableRoaringBitmap.bitmap_of(1, 5, 70000)
    assert isinstance(m, MutableRoaringBitmap)
    assert sorted(m.to_array().tolist()) == [1, 5, 70000]
    assert isinstance(MutableRoaringBitmap.bitmap_of(3), MutableRoaringBitmap)
    removed = ImmutableRoaringBitmap.remove(imm, 0, 1 << 32)
    assert removed.is_empty() and imm.cardinality > 0  # source untouched
    partial = ImmutableRoaringBitmap.remove(imm, 0, int(imm.to_array()[1]))
    assert partial.cardinality == imm.cardinality - 1
    mm = MutableRoaringBitmap.bitmap_of(9, 10)
    mm.remove(9)  # point removal still works on the mutable class
    assert mm.to_array().tolist() == [10]
    assert imm.to_mutable().get_mappeable_roaring_array().keys is not None


class TestBufferBatchIteratorSweep:
    """ImmutableRoaringBitmapBatchIteratorTest analogs over BOTH tiers:
    randomized seek to present/absent/beyond values, and the
    zero-length-run seek regression (:185-213)."""

    def _tiers(self, rb):
        yield rb
        yield ImmutableRoaringBitmap(rb.serialize())

    @pytest.mark.parametrize("batch", [1, 7, 128, 65536])
    def test_advance_to_random_positions(self, rng, batch):
        vals = np.unique(np.concatenate([
            rng.integers(0, 1 << 22, 30000),
            np.arange(5 << 16, (5 << 16) + 4000)])).astype(np.uint32)
        src = RoaringBitmap.from_values(vals)
        src.run_optimize()
        for rb in self._tiers(src):
            for target_kind in ("present", "absent", "beyond"):
                if target_kind == "present":
                    t = int(vals[int(rng.integers(vals.size))])
                elif target_kind == "absent":
                    t = int(vals[-1]) // 2
                    while t in src:
                        t += 1
                else:
                    t = int(vals[-1]) + 1
                it = rb.get_batch_iterator(batch)
                it.advance_if_needed(t)
                got = (np.concatenate(list(it)) if it.has_next()
                       else np.empty(0, np.uint32))
                want = vals[vals >= t]
                np.testing.assert_array_equal(got, want, err_msg=target_kind)

    def test_zero_length_run_seek(self):
        # :200-213 — runOptimized container with single-value runs; seeking
        # to each member must land exactly on it
        vals = np.array([10, 11, 12, 13, 14, 15, 18, 20, 21, 22, 23, 24],
                        dtype=np.uint32)
        src = RoaringBitmap.from_values(vals)
        src.run_optimize()
        for rb in self._tiers(src):
            for number in (10, 11, 12, 13, 14, 15, 18, 20, 21, 23, 24):
                it = rb.get_batch_iterator(10)
                it.advance_if_needed(number)
                assert it.has_next()
                batch = it.next_batch()
                assert number in batch.tolist()

    def test_timely_termination(self):
        # :165-183 — an exhausted iterator reports has_next() False and
        # returns empty batches, also after a beyond-last seek — on BOTH
        # tiers (the reference test targets the byte-backed class)
        for rb in self._tiers(RoaringBitmap.bitmap_of(1, 2, 3)):
            it = rb.get_batch_iterator(10)
            assert it.next_batch().size == 3
            assert not it.has_next() and it.next_batch().size == 0
            it2 = rb.get_batch_iterator(10)
            it2.advance_if_needed(100)
            assert not it2.has_next() and it2.next_batch().size == 0
