"""Buffer tier tests — ImmutableRoaringBitmap over bytes and mmap
(the reference's buffer/ suite incl. TestMemoryMapping), algebra producing
in-RAM results, and BufferFastAggregation-style wide ops on immutable
inputs."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap, MutableRoaringBitmap
from roaringbitmap_tpu.parallel import aggregation

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"


@pytest.fixture(scope="module")
def sample(rng):
    vals = rng.integers(0, 1 << 24, 40000, dtype=np.uint32)
    rb = RoaringBitmap.from_values(vals)
    rb.run_optimize()
    return rb


@pytest.fixture(scope="module")
def imm(sample):
    return ImmutableRoaringBitmap(sample.serialize())


class TestImmutable:
    def test_header_only_accessors(self, sample, imm):
        assert imm.cardinality == sample.cardinality
        assert not imm.is_empty()
        assert imm.has_run_compression() == sample.has_run_compression()
        assert imm.serialized_size_in_bytes() == sample.serialized_size_in_bytes()

    def test_point_ops(self, sample, imm):
        arr = sample.to_array()
        for x in arr[::5000]:
            assert int(x) in imm
            assert imm.rank(int(x)) == sample.rank(int(x))
        assert imm.first() == sample.first()
        assert imm.last() == sample.last()
        for j in range(0, sample.cardinality, 7001):
            assert imm.select(j) == sample.select(j)

    def test_lazy_container_cache(self, imm, sample):
        fresh = ImmutableRoaringBitmap(sample.serialize())
        assert len(fresh._cache) == 0
        fresh.contains(int(sample.first()))
        assert len(fresh._cache) == 1  # only the touched container parsed

    def test_algebra_returns_inram(self, sample, imm, rng):
        other = RoaringBitmap.from_values(
            rng.integers(0, 1 << 24, 10000, dtype=np.uint32))
        for res, ref in [
            (imm & other, sample & other),
            (imm | other, sample | other),
            (imm ^ other, sample ^ other),
            (imm - other, sample - other),
        ]:
            assert isinstance(res, RoaringBitmap)
            assert res == ref
        # immutable ∘ immutable too
        o_imm = ImmutableRoaringBitmap(other.serialize())
        assert (imm & o_imm) == (sample & other)

    def test_serialize_verbatim(self, sample, imm):
        assert imm.serialize() == sample.serialize()

    def test_roundtrip_and_conversion(self, sample, imm):
        assert imm.to_bitmap() == sample
        m = imm.to_mutable()
        assert isinstance(m, MutableRoaringBitmap)
        m.add(0xFEEDBEEF)
        assert 0xFEEDBEEF in m and 0xFEEDBEEF not in imm
        assert m.to_immutable().cardinality == sample.cardinality + 1

    def test_mutable_copy_does_not_alias(self, sample, imm):
        """to_mutable/to_bitmap must not share the cached container list:
        point mutations on the copy rebind list entries."""
        snapshot = imm.to_bitmap().to_array()
        m = imm.to_mutable()
        m.add(0xFEEDBEEF)
        m.remove(int(snapshot[0]))
        assert np.array_equal(imm.to_bitmap().to_array(), snapshot)

    def test_view_into_larger_frame(self, sample):
        """An embedded bitmap mid-buffer, like ByteBuffer slices."""
        blob = b"\xAA" * 37 + sample.serialize() + b"\xBB" * 11
        imm = ImmutableRoaringBitmap(memoryview(blob)[37:])
        assert imm.cardinality == sample.cardinality
        assert imm.to_bitmap() == sample

    def test_mmap_file(self, sample, tmp_path):
        """Real memory-mapped file (TestMemoryMapping.java analog)."""
        path = os.path.join(tmp_path, "bitmap.bin")
        with open(path, "wb") as f:
            f.write(sample.serialize())
        imm = ImmutableRoaringBitmap.mapped(path)
        assert imm.cardinality == sample.cardinality
        assert imm.first() == sample.first()
        assert (imm & sample) == sample
        assert imm.to_bitmap() == sample

    @pytest.mark.skipif(not os.path.isdir(TESTDATA),
                        reason="reference corpus not mounted")
    @pytest.mark.parametrize("name,card", [("bitmapwithruns.bin", 200100),
                                           ("bitmapwithoutruns.bin", 200100)])
    def test_reference_fixture(self, name, card):
        with open(os.path.join(TESTDATA, name), "rb") as f:
            data = f.read()
        imm = ImmutableRoaringBitmap(data)
        assert imm.cardinality == card
        assert imm.serialize() == data


class TestBufferWideAggregation:
    """BufferFastAggregation analog: wide device ops straight off
    immutable (serialized) inputs."""

    def test_wide_or_on_immutables(self, rng):
        arrs = [rng.integers(0, 1 << 20, 5000, dtype=np.uint32)
                for _ in range(16)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        got = aggregation.or_(imms, engine="xla")
        oracle = np.unique(np.concatenate(arrs))
        assert np.array_equal(got.to_array(), oracle)

    def test_wide_and_on_immutables(self, rng):
        base = np.unique(rng.integers(0, 1 << 18, 3000, dtype=np.uint32))
        arrs = [np.union1d(base, rng.integers(0, 1 << 18, 500, dtype=np.uint32))
                for _ in range(6)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        got = aggregation.and_(imms)
        oracle = arrs[0]
        for a in arrs[1:]:
            oracle = np.intersect1d(oracle, a)
        assert np.array_equal(got.to_array(), oracle)

    def test_device_set_from_immutables(self, rng):
        arrs = [rng.integers(0, 1 << 20, 4000, dtype=np.uint32)
                for _ in range(8)]
        imms = [ImmutableRoaringBitmap(
            RoaringBitmap.from_values(a).serialize()) for a in arrs]
        ds = aggregation.DeviceBitmapSet(imms)
        got = ds.aggregate("or", engine="xla")
        assert np.array_equal(got.to_array(), np.unique(np.concatenate(arrs)))
