"""Nastiest reference edge cases, ported as explicit unit tests.

Round-3 verdict item 10: the reference spends thousands of LoC on container
boundary cases (TestRunContainer.java is 4,000 LoC alone); the fuzz catalog
covers the bulk statistically, but the cases below are deterministic
regressions the reference found worth pinning.  Each test cites its source.

Ports are at the public-API level: this package's containers are value/SoA
based by design (SURVEY §7), so container-internal assertions (getSizeInBytes,
nbrruns) translate to observable behavior — membership, cardinality,
container-kind selection, and serialized-form parity.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.core import containers as C
from roaringbitmap_tpu.core.rangebitmap import RangeBitmap

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_corpus = pytest.mark.skipif(not os.path.isdir(TESTDATA),
                                  reason="reference corpus not mounted")


def _read_int_list(name: str) -> np.ndarray:
    with open(os.path.join(TESTDATA, name)) as f:
        return np.array([int(x) for x in f.read().replace("\n", ",").split(",")
                         if x.strip()], dtype=np.int64)


def _oracle_set(rb: RoaringBitmap) -> set[int]:
    return set(rb.to_array().tolist())


# ------------------------------------------------------------ offset corpus
# TestConcatenation.java:33-66 (testElementwiseOffsetAppliedCorrectly /
# testCardinalityPreserved): the offset_failure_case corpus captures addOffset
# bugs where shifted containers straddle chunk boundaries.

OFFSET_CASES = [("testIssue260.txt", 5950),
                ("offset_failure_case_1.txt", 20),
                ("offset_failure_case_2.txt", 20),
                ("offset_failure_case_3.txt", 20)]


@needs_corpus
@pytest.mark.parametrize("name,offset", OFFSET_CASES)
def test_offset_corpus_elementwise(name, offset):
    # TestConcatenation.testElementwiseOffsetAppliedCorrectly:81-89
    vals = _read_int_list(name)
    rb = RoaringBitmap.from_values(vals.astype(np.uint32))
    shifted = rb.add_offset(offset)
    np.testing.assert_array_equal(
        shifted.to_array().astype(np.int64), vals + offset)
    # TestConcatenation.testCardinalityPreserved:100-105
    assert shifted.cardinality == rb.cardinality


@needs_corpus
@pytest.mark.parametrize("name,offset", OFFSET_CASES)
def test_offset_corpus_roundtrip(name, offset):
    # negated offset must restore the original (no value exits [0, 2^32))
    vals = _read_int_list(name)
    rb = RoaringBitmap.from_values(vals.astype(np.uint32))
    assert rb.add_offset(offset).add_offset(-offset) == rb


@needs_corpus
@pytest.mark.parametrize("name,offset", OFFSET_CASES)
def test_offset_corpus_buffer_variant(name, offset):
    # TestConcatenation.testElementwiseOffsetAppliedCorrectlyBuffer:92-97 /
    # testCardinalityPreservedBuffer:108-112: the mutable buffer twin's
    # offset, via the immutable pairing
    from roaringbitmap_tpu.buffer import (ImmutableRoaringBitmap,
                                          MutableRoaringBitmap)

    vals = _read_int_list(name)
    rb = RoaringBitmap.from_values(vals.astype(np.uint32))
    mut = ImmutableRoaringBitmap(rb.serialize()).to_mutable()
    assert isinstance(mut, MutableRoaringBitmap)
    shifted = mut.add_offset(offset)
    np.testing.assert_array_equal(
        shifted.to_array().astype(np.int64), vals + offset)
    assert shifted.cardinality == rb.cardinality


def _mixed_container_bitmap(seed: int) -> RoaringBitmap:
    """A bitmap with an array, a run, and a bitmap container at distinct
    chunks — the testCase().withBitmapAt/withRunAt/withArrayAt construction
    of TestConcatenation.java:40-45."""
    rng = np.random.default_rng(seed)
    rb = RoaringBitmap()
    rb.add_many((rng.choice(1 << 16, size=100, replace=False)
                 ).astype(np.uint32))                       # array chunk 0
    rb.add_range((1 << 16) + 1000, (1 << 16) + 9000)        # run chunk 1
    rb.add_many(((2 << 16)
                 + rng.choice(1 << 16, size=9000, replace=False)
                 ).astype(np.uint32))                       # bitmap chunk 2
    return rb


@pytest.mark.parametrize("offset", [20, 1 << 16, -20, 65516])
@pytest.mark.parametrize("seed", [0, 1])
def test_offset_mixed_containers(offset, seed):
    # TestConcatenation.java:40-63 — container-kind mixes under aligned
    # (1 << 16) and awkward (20) offsets
    rb = _mixed_container_bitmap(seed)
    vals = rb.to_array().astype(np.int64) + offset
    vals = vals[(vals >= 0) & (vals <= 0xFFFFFFFF)]
    shifted = rb.add_offset(offset)
    np.testing.assert_array_equal(shifted.to_array().astype(np.int64), vals)


# ----------------------------------------------------- prevvalue regression
def test_previous_value_regression():
    # PreviousValueTest.java:15-24: previousValue beyond the last set bit
    # must return last(), not miss the final container
    if os.path.isdir(TESTDATA):
        vals = _read_int_list("prevvalue-regression.txt")
    else:
        vals = np.array([5, 1 << 16, 1828800000], dtype=np.int64)
    rb = RoaringBitmap.from_values(vals.astype(np.uint32))
    assert rb.previous_value(1828834057) == rb.last()


# ----------------------------------------------------- rangebitmap regression
@needs_corpus
def test_rangebitmap_between_regression():
    # RangeBitmapTest.betweenRegressionTest:50-65: between(x, x+1) must equal
    # eq(x) | eq(x+1) on the regression column
    vals = _read_int_list("rangebitmap_regression.txt")
    app = RangeBitmap.appender(2175288)
    app.add_many(vals.astype(np.uint64))
    rbm = app.build()
    for i in range(4):
        lo = 263501 + i
        assert rbm.between(lo, lo + 1) == (rbm.eq(lo) | rbm.eq(lo + 1))


@pytest.mark.parametrize("size", [0xFFFF, 0x10001, 100_000])
def test_rangebitmap_contiguous_values_multi_chunk(size):
    # RangeBitmapTest.testInsertContiguousValues:68-93: contiguous column
    # values crossing the 2^16 row-chunk boundary; every threshold form
    # checked at decade points
    app = RangeBitmap.appender(size)
    app.add_many(np.arange(size, dtype=np.uint64))
    rbm = app.build()
    assert rbm.lte(size) == RoaringBitmap.from_range(0, size)
    upper = 1
    while upper < size:
        expected = RoaringBitmap.from_range(0, upper + 1)
        assert rbm.lte(upper) == expected
        assert rbm.lte_cardinality(upper) == expected.cardinality
        assert rbm.lt(upper) == RoaringBitmap.from_range(0, upper)
        assert rbm.lt_cardinality(upper) == upper
        assert rbm.eq(upper) == RoaringBitmap.bitmap_of(upper)
        upper *= 10
    lower = 1
    while lower < size:
        expected = RoaringBitmap.from_range(lower, size)
        assert rbm.gte(lower) == expected
        assert rbm.gte_cardinality(lower) == expected.cardinality
        assert rbm.gt(lower) == RoaringBitmap.from_range(lower + 1, size)
        lower *= 10


def test_rangebitmap_empty_and_zero():
    # RangeBitmapTest.testLessThanZeroEmpty:120-127 and
    # testSerializeEmpty:291-300
    app = RangeBitmap.appender(10)
    rbm = app.build()
    assert rbm.lte(5).is_empty() and rbm.row_count == 0
    assert RangeBitmap.map(rbm.serialize()).lt_cardinality(10) == 0
    app2 = RangeBitmap.appender(100)
    app2.add_many(np.arange(50, dtype=np.uint64))
    rbm2 = app2.build()
    assert rbm2.lt(0).is_empty()  # lt(0): nothing below the minimum


# ------------------------------------------------- 0xFFFF-adjacent run cases
def test_run_reaching_65535():
    # TestRunContainer.testToString:3172-3176: run [32200,35000) plus the
    # final value 65535 — the run codec's length field must not wrap
    rb = RoaringBitmap()
    rb.add_range(32200, 35000)
    rb.add(65535)
    assert rb.run_optimize()
    c = rb.containers[0]
    assert isinstance(c, C.RunContainer)
    np.testing.assert_array_equal(
        c.runs.astype(np.int64), [32200, 2799, 65535, 0])
    assert rb.cardinality == 2801 and rb.last() == 65535
    assert RoaringBitmap.deserialize(rb.serialize()) == rb


def test_run_iadd_iremove_full_tail():
    # TestRunContainer.iremove17:1608-1612: add [37543, 65536) then remove
    # [9795, 65536) leaves nothing
    rb = RoaringBitmap()
    rb.add_range(37543, 65536)
    rb.remove_range(9795, 65536)
    assert rb.cardinality == 0 and rb.is_empty()


def test_run_add_65534_65536():
    # TestRunContainer.testRangeConsumer:3915-3929 entry set: runs fusing at
    # the top of the chunk (65530 alone, then [65534, 65536))
    rb = RoaringBitmap()
    rb.add_range(3, 5)
    rb.add_range(7, 9)
    rb.add(10)
    rb.add(65530)
    rb.add_range(65534, 65536)
    assert rb.to_array().tolist() == [3, 4, 7, 8, 10, 65530, 65534, 65535]
    rb.run_optimize()
    assert RoaringBitmap.deserialize(rb.serialize()) == rb


def test_run_fuse_with_next_and_previous():
    # TestRunContainer.addRangeAndFuseWithNextValueLength:234-249 and
    # addRangeAndFuseWithPreviousValueLength:252-265: [10,20)+[21,30) add
    # [15,21) -> ONE run [10,30) (serialized run form is 2 + 4*1 bytes...
    # observable here as number_of_runs == 1)
    rb = RoaringBitmap()
    rb.add_range(10, 20)
    rb.add_range(21, 30)
    rb.add_range(15, 21)
    assert rb.cardinality == 20
    assert all(rb.contains(i) for i in range(10, 30))
    assert C.number_of_runs(rb.containers[0].values()) == 1

    rb2 = RoaringBitmap()
    rb2.add_range(10, 20)
    rb2.add_range(20, 30)
    assert rb2.cardinality == 20
    assert C.number_of_runs(rb2.containers[0].values()) == 1


def test_full_chunk_run_constructor():
    # TestRunContainer.testRangeConstructor:3563-3567: [0, 1<<16) is full
    rb = RoaringBitmap.from_range(0, 1 << 16)
    assert rb.cardinality == 65536
    rb.run_optimize()
    c = rb.containers[0]
    assert isinstance(c, C.RunContainer) and c.cardinality == 65536
    np.testing.assert_array_equal(c.runs.astype(np.int64), [0, 65535])
    assert RoaringBitmap.deserialize(rb.serialize()) == rb


def test_first_unsigned_top_half():
    # TestRunContainer.testFirstUnsigned:3310-3314: [32768, 65536) — first()
    # must treat the chunk values as unsigned
    rb = RoaringBitmap()
    rb.add_range(32768, 65536)
    assert rb.first() == 32768
    assert rb.last() == 65535


# ------------------------------------------------- promotion / demotion chains
def test_promotion_chain_at_4096():
    # ArrayContainer.DEFAULT_MAX_SIZE = 4096 (ArrayContainer.java:27);
    # TestArrayContainer promotion coverage: adding the 4097th value
    # promotes, removing back demotes (BitmapContainer demote-on-remove)
    rb = RoaringBitmap()
    rb.add_many(np.arange(0, 2 * 4096, 2, dtype=np.uint32))  # 4096 values
    assert isinstance(rb.containers[0], C.ArrayContainer)
    rb.add(1)                                                # 4097th
    assert isinstance(rb.containers[0], C.BitmapContainer)
    rb.remove(1)
    assert isinstance(rb.containers[0], C.ArrayContainer)
    assert rb.cardinality == 4096


def test_promotion_chain_full_then_punch():
    # TestBitmapContainer-style full-chunk chain: fill the chunk, punch a
    # hole, refill; kind selection and cardinality must track exactly
    rb = RoaringBitmap.from_range(0, 1 << 16)
    rb.remove(30000)
    assert rb.cardinality == 65535
    assert isinstance(rb.containers[0], C.BitmapContainer)
    rb.add(30000)
    assert rb.cardinality == 65536
    rb.remove_range(0, 61440)  # leaves 4096 values -> array-size boundary
    assert rb.cardinality == 4096
    assert isinstance(rb.containers[0], C.ArrayContainer)


def test_flip_range_full_chunk_boundaries():
    # TestRunContainer inot14/inot15-style complements crossing the chunk
    # top: flip [65000, 65536) twice is identity; flip across chunks matches
    # the set oracle
    rng = np.random.default_rng(7)
    vals = rng.choice(1 << 17, size=5000, replace=False).astype(np.uint32)
    rb = RoaringBitmap.from_values(vals)
    before = _oracle_set(rb)
    rb.flip_range(65000, 65536)
    rb.flip_range(65000, 65536)
    assert _oracle_set(rb) == before
    rb.flip_range(60000, 70000)
    expect = before ^ set(range(60000, 70000))
    assert _oracle_set(rb) == expect


def test_run_intersects_range_boundary():
    # TestRunContainer.testIntersects:3161-3165: runs {41+15, 215+0, ...};
    # intersects(57, 215) is FALSE (the 215 run starts exactly at the
    # exclusive end)
    rb = RoaringBitmap()
    for start, length in ((41, 15), (215, 0), (217, 2790), (3065, 170),
                          (3269, 422), (3733, 43), (3833, 16), (3852, 7),
                          (3662, 3), (3901, 2)):
        rb.add_range(start, start + length + 1)
    assert not rb.intersects_range(57, 215)
    assert rb.intersects_range(57, 216)


@pytest.mark.parametrize("base_vals,flip", [
    # TestRunContainer.inot1:952: empty flip range is the identity
    ([0, 2, 55, 64, 256], (64, 64)),
    # inot2/inot3-style: flip overlapping the value set's edges
    ([0, 2, 55, 64, 256], (64, 65)),
    ([0, 2, 55, 64, 256], (0, 65)),
    ([0, 2, 55, 64, 256], (2, 257)),
    # inot7-style: a solid run [500,505) flipped across its middle/ends
    ([500, 501, 502, 503, 504], (502, 505)),
    ([500, 501, 502, 503, 504], (498, 507)),
    ([500, 501, 502, 503, 504], (500, 505)),
    # inot14/inot15-style: flips touching the chunk top
    ([65530, 65533, 65535], (65529, 65536)),
    ([65530, 65533, 65535], (65535, 65536)),
    # cross-chunk flip over values in two chunks
    ([65535, 65536, 70000], (65000, 70001)),
])
def test_flip_range_endpoint_sweep(base_vals, flip):
    # the TestRunContainer inot1-15 block (TestRunContainer.java:952-1260),
    # as RoaringBitmap.flip_range vs the set oracle; container kind after
    # the flip is the implementation's choice — contents must be exact
    rb = RoaringBitmap.from_values(np.array(base_vals, np.uint32))
    rb.run_optimize()
    lo, hi = flip
    rb.flip_range(lo, hi)
    expect = set(base_vals) ^ set(range(lo, hi))
    assert _oracle_set(rb) == expect
    # involution: flipping again restores the original
    rb.flip_range(lo, hi)
    assert _oracle_set(rb) == set(base_vals)


@pytest.mark.parametrize("elements,begin,end,expected", [
    # TestBufferRangeCardinality.data:21-28 (cardinalityInBitmapWordRange)
    ([1, 3, 5, 7, 9], 3, 8, 3),
    ([1, 3, 5, 7, 9], 2, 8, 3),
    ([1, 3, 5, 7, 9], 3, 7, 2),
    ([1, 3, 5, 7, 9], 0, 7, 3),
    ([1, 3, 5, 7, 9], 0, 6, 3),
    ([1, 3, 5, 7, 9, 0x7FFF], 0, 0x8000, 6),
    ([1, 10000, 25000, 0x7FFE], 0, 0x7FFF, 4),
    ([1 << 3, 1 << 8, 511, 512, 513, 1 << 12, 1 << 14], 0, 0x7FFF, 7),
])
def test_buffer_range_cardinality_word_boundaries(elements, begin, end,
                                                  expected):
    # host tier, byte-backed immutable tier, and the device image must all
    # count the same word-boundary-straddling ranges
    from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

    rb = RoaringBitmap.from_values(np.array(elements, np.uint32))
    assert rb.range_cardinality(begin, end) == expected
    imm = ImmutableRoaringBitmap(rb.serialize())
    assert imm.range_cardinality(begin, end) == expected
    db = DeviceBitmap.from_host(rb)
    assert db.range_cardinality(begin, end) == expected


def test_add_n_window():
    # RoaringBitmap.addN:1199 — the partial-array add (offset, length)
    vals = np.array([9, 1, 5, 70000, 3, 2], np.uint32)
    rb = RoaringBitmap()
    rb.add_n(vals, 1, 3)
    assert rb.to_array().tolist() == [1, 5, 70000]
    rb.add_n(vals, 0, 0)  # empty window is a no-op
    assert rb.cardinality == 3
    with pytest.raises(IndexError):
        rb.add_n(vals, 4, 3)
    with pytest.raises(IndexError):
        rb.add_n(vals, -1, 2)


# ------------------------------------------------ batch iterator regressions
def _batch_it(rb, batch_size):
    from roaringbitmap_tpu.core.iterators import RoaringBatchIterator

    return RoaringBatchIterator(rb, batch_size)


def test_batch_iterator_timely_termination():
    # RoaringBitmapBatchIteratorTest.testTimelyTermination:181-190 and
    # testTimelyTerminationAfterAdvanceIfNeeded:193-199
    rb = RoaringBitmap.bitmap_of(8511)
    it = _batch_it(rb, 10)
    assert it.has_next()
    batch = it.next_batch()
    assert batch.tolist() == [8511]
    assert not it.has_next()

    it2 = _batch_it(rb, 10)
    assert it2.has_next()
    it2.advance_if_needed(8512)
    assert not it2.has_next()


def test_batch_iterator_advance_before_first_key():
    # testBatchIteratorWithAdvanceIfNeeded:202-214: seeking to 6 when the
    # first container lives at chunk 3 must not skip it
    rb = RoaringBitmap.bitmap_of(3 << 16, (3 << 16) + 5, (3 << 16) + 10)
    it = _batch_it(rb, 10)
    it.advance_if_needed(6)
    assert it.has_next()
    batch = it.next_batch()
    assert batch.tolist() == [3 << 16, (3 << 16) + 5, (3 << 16) + 10]


@pytest.mark.parametrize("number", [10, 11, 12, 13, 14, 15, 18, 20, 21,
                                    23, 24])
def test_batch_iterator_advance_in_run(number):
    # testBatchIteratorWithAdvancedIfNeededWithZeroLengthRun:217-229
    rb = RoaringBitmap.bitmap_of(10, 11, 12, 13, 14, 15, 18, 20, 21, 22,
                                 23, 24)
    rb.run_optimize()
    it = _batch_it(rb, 10)
    it.advance_if_needed(number)
    assert it.has_next()
    batch = it.next_batch()
    assert number in batch.tolist()


def test_batch_iterator_fills_across_containers():
    # testBatchIteratorFillsBufferAcrossContainers:231-246: batches span
    # container boundaries
    vals = [3 << 4, 3 << 8, 3 << 12, 3 << 16, 3 << 20, 3 << 24, 3 << 28]
    rb = RoaringBitmap.bitmap_of(*vals)
    assert rb.container_count() == 5
    it = _batch_it(rb, 3)
    got = []
    while it.has_next():
        got.extend(it.next_batch().tolist())
    assert got == vals


# --------------------------------------------- next/previous value boundaries
def test_next_value_word_boundaries():
    # TestBitmapContainer.testNextValue2/testNextValueBetweenRuns:1036-1056 —
    # [64,129) and [256,321) probe exactly at 64-bit word boundaries
    rb = RoaringBitmap()
    rb.add_range(64, 129)
    rb.add_range(256, 321)
    assert rb.next_value(0) == 64
    assert rb.next_value(64) == 64
    assert rb.next_value(65) == 65
    assert rb.next_value(128) == 128
    assert rb.next_value(129) == 256
    assert rb.next_value(512) == -1


def test_next_value_after_end_and_unsigned():
    # TestBitmapContainer.testNextValueAfterEnd:1030-1033 and
    # testNextValueUnsigned:1076-1083
    rb = RoaringBitmap.from_values(np.array([10, 20, 30], np.uint32))
    assert rb.next_value(31) == -1
    hi = 1 << 15
    rb2 = RoaringBitmap.from_values(np.array([hi | 5, hi | 7], np.uint32))
    assert rb2.next_value(hi | 4) == (hi | 5)
    assert rb2.next_value(hi | 5) == (hi | 5)
    assert rb2.next_value(hi | 6) == (hi | 7)
    assert rb2.next_value(hi | 8) == -1


@needs_corpus
def test_ornot_fuzz_regression():
    # TestRoaringBitmapOrNot.testBigOrNot/testBigOrNotStatic:382-425: the
    # fuzz-caught orNot failure, replayed from the serialized repro pair
    import base64
    import json

    from roaringbitmap_tpu.core.bitmap import or_not

    with open(os.path.join(TESTDATA, "ornot-fuzz-failure.json")) as f:
        info = json.load(f)
    l_rb = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][0]))
    r_rb = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][1]))
    limit = l_rb.last() + 1
    rng_bm = RoaringBitmap()
    rng_bm.add_range(0, limit)
    expected = l_rb | (rng_bm - r_rb)
    assert or_not(l_rb, r_rb, limit) == expected


def test_previous_value_word_boundaries():
    # TestBitmapContainer.testPreviousValue1:1086-1093
    rb = RoaringBitmap()
    rb.add_range(64, 129)
    assert rb.previous_value(0) == -1
    assert rb.previous_value(63) == -1
    assert rb.previous_value(64) == 64
    assert rb.previous_value(128) == 128
    assert rb.previous_value(200) == 128


# --------------------------------------------- numbered issue regressions
# A targeted pass over TestRoaringBitmap.java's numbered-issue regressions.

def test_ornot_regressions():
    # TestRoaringBitmap.orNotRegressionTest:2376-2385 (must not throw) and
    # orNotZeroRangeEndPreservesBitmap:2388-2398
    from roaringbitmap_tpu.core.bitmap import or_not

    one = RoaringBitmap()
    other = RoaringBitmap()
    other.add_range(0, 3)
    or_not(one, other, 3)  # empty |~ [0,3) over [0,3) — no crash

    one = RoaringBitmap.bitmap_of(32)
    other = RoaringBitmap()
    other.add_range(0, 100)
    assert or_not(one, other, 0) == RoaringBitmap.bitmap_of(32)


def test_issue418_offset_roundtrip_high():
    # TestRoaringBitmap.issue418:5252-5271: offsets that push the single
    # bit across the 0xFFFF0000 chunk boundary and back
    rb = RoaringBitmap.bitmap_of(0)
    for s in (100, 0xFFFF0000, 0xFFFF0001):
        shifted = rb.add_offset(s)
        assert shifted.contains(s) and shifted.cardinality == 1
        back = shifted.add_offset(-s)
        assert back.contains(0) and back.cardinality == 1


def test_issue564_previous_value_before_first():
    # TestRoaringBitmap.testPreviousValueRegression:5386-5390 (issue 564)
    assert RoaringBitmap.bitmap_of(27399807).previous_value(403042) == -1
    assert RoaringBitmap().previous_value(403042) == -1


def test_previous_value_absent_target_container():
    # TestRoaringBitmap.testPreviousValue_AbsentTargetContainer:5393-5401;
    # Java's int -1 is unsigned 0xFFFFFFFF here
    rb = RoaringBitmap.bitmap_of(0xFFFFFFFF, 2, 3, 131072)
    assert rb.previous_value(65536) == 3
    assert rb.previous_value(0x7FFFFFFF) == 131072
    assert rb.previous_value((1 << 32) - 131072) == 131072
    assert RoaringBitmap.bitmap_of(131072).previous_value(65536) == -1
    # testPreviousValue_LastReturnedAsUnsignedLong:5404-5408
    vals = [(1 << 32) - 650002, (1 << 32) - 650001, (1 << 32) - 650000]
    rb2 = RoaringBitmap.bitmap_of(*vals)
    assert rb2.previous_value(0xFFFFFFFF) == (1 << 32) - 650000


def test_issue285_range_cardinality_at_boundary():
    # TestRoaringBitmap.testRangeCardinalityAtBoundary:5410-5416
    rb = RoaringBitmap.bitmap_of(66236)
    assert rb.range_cardinality(60000, 70000) == 1
    # testNextValueArray:5418-5423
    rb2 = RoaringBitmap.bitmap_of(0, 1, 2, 4, 6)
    assert rb2.next_value(7) == -1


def test_issue370_equals_after_run_optimize():
    # TestRoaringBitmap.regressionTestEquals370:5425-5439: equality must
    # hold across container-kind differences, and run_optimize must not
    # make two genuinely different bitmaps compare equal
    a = [239, 240, 241, 242, 243, 244, 259, 260, 261, 262, 263, 264, 265,
         266, 267, 268, 269, 270, 273, 274, 275, 276, 277, 278, 398, 399,
         400, 401, 402, 403, 404, 405, 406, 408, 409, 410, 411, 412, 413,
         420, 421, 422, 509, 510, 511, 512, 513, 514, 539, 540, 541, 542,
         543, 544, 547, 548, 549, 550, 551, 552, 553, 554, 555, 556, 557,
         558, 578, 579, 580, 581, 582, 583, 584, 585, 586, 587, 588, 589,
         590, 591, 592, 593, 594, 595, 624, 625, 634, 635, 636, 649, 650,
         651, 652, 653, 654, 714, 715, 716, 718, 719, 720, 721, 722, 723,
         724, 725, 726, 728, 729, 730, 731, 732, 733, 734, 735, 736, 739,
         740, 741, 742, 743, 744, 771, 772, 773]
    b = list(a)
    b[74:79] = [586, 607, 608, 634, 635]  # diverge, same lengths region
    rb_a = RoaringBitmap.from_values(np.array(a, np.uint32))
    rb_b = RoaringBitmap.from_values(np.array(sorted(set(b)), np.uint32))
    assert rb_a != rb_b
    rb_a.run_optimize()
    assert rb_a != rb_b
    rb_b.run_optimize()
    assert rb_a != rb_b
    # and the positive direction: kinds differ, contents equal
    rb_c = RoaringBitmap.from_values(np.array(a, np.uint32))
    assert rb_a == rb_c


def test_issue377_remove_range_after_point_removes():
    # TestRoaringBitmap.regressionTestRemove377:5441-5453
    rb = RoaringBitmap()
    rb.add_range(0, 64)
    for i in range(64):
        if i not in (30, 32):
            rb.remove(i)
    rb.remove_range(0, 31)
    assert not rb.contains(30)
    assert rb.contains(32)


def test_issue623_contains_range_at_chunk_boundary():
    # TestRoaringBitmap.issue623:5539-5552 (boundary essence; the 10^7
    # loop is compressed to ranges crossing the 65536 boundary)
    rb = RoaringBitmap.bitmap_of(65535, 65536)
    assert rb.contains(65535) and rb.contains(65536)
    assert rb.contains_range(65535, 65536)
    assert rb.contains_range(65535, 65537)
    rb.add_range(1, 200000)
    for i in (1, 65535, 65536, 131071, 131072, 199999):
        assert rb.contains_range(i, i + 1), i


def test_issue1235_single_flip():
    # TestRoaringBitmap.test1235:5554-5559
    rb = RoaringBitmap.bitmap_of(1, 2, 3, 5)
    rb.flip_range(4, 5)
    assert rb == RoaringBitmap.bitmap_of(1, 2, 3, 4, 5)


# ---------------------------------------------- 64-bit tier regressions
# TestRoaring64Bitmap.java's numbered issues, at the Roaring64Bitmap level.

def _rb64(*vals):
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    return Roaring64Bitmap.bitmap_of(*vals)


def test_issue537_and_with_absent_member():
    # TestRoaring64Bitmap.testIssue537:2079-2093: AND against a bitmap
    # sharing the high-48 key must not resurrect an absent member
    vals = [275845652, 275845746, 275846148, 275847372, 275847380,
            275847388, 275847459, 275847528, 275847586, 275847588,
            275847600, 275847607, 275847610, 275847613, 275847631]
    a = _rb64(275846320)
    b = _rb64(275846320)
    c = _rb64(*vals)
    c.iand(b)
    assert not c.contains(275846320)
    c.iand(a)
    assert not c.contains(275846320)


def test_issue558_add_remove_churn():
    # TestRoaring64Bitmap.testIssue558:2097-2104: random add/remove churn
    # over the full signed-long range must not corrupt the key index
    # (compressed: 20k iterations instead of 1M)
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    rng = np.random.default_rng(1234)
    rb = Roaring64Bitmap()
    adds = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    dels = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    expect: set[int] = set()
    for a, d in zip(adds.tolist(), dels.tolist()):
        rb.add(a)
        expect.add(a)
        rb.remove(d)
        expect.discard(d)
    assert rb.cardinality == len(expect)
    assert set(rb.to_array().tolist()) == expect


def test_issue577_for_each_in_range():
    # TestRoaring64Bitmap.testIssue577Case1/2/3:2107-2161: forEachInRange
    # over >32-bit values (range start/length in the reference's
    # (start, length) form -> [start, start+length) here)
    b1 = _rb64(45011744312, 45008074636, 41842920068, 41829418930,
               40860008694, 40232297287, 40182908832, 40171852270,
               39933922233, 39794107638)
    assert next(b1.reverse_long_iterator()) == 45011744312
    b1.for_each_in_range(46000000000, 47000000000,
                         lambda v: pytest.fail(f"no values here: {v}"))

    b2 = _rb64(30385375409, 30399869293, 34362979339, 35541844320,
               36637965094)
    seen = []
    # the reference's [33e9, 34e9) window contains NO member (its consumer
    # assertion is vacuous); assert that explicitly, then widen to 35e9
    # where exactly one member falls
    b2.for_each_in_range(33000000000, 34000000000, seen.append)
    assert seen == []
    b2.for_each_in_range(33000000000, 35000000000, seen.append)
    assert seen == [34362979339]

    b3 = _rb64(14510802367, 26338197481, 32716744974, 32725817880,
               35679129730)
    seen = []
    b3.for_each_in_range(32000000000, 33000000000, seen.append)
    assert seen == [32716744974, 32725817880]


def test_issue580_iterate_sparse_high_keys():
    # TestRoaring64Bitmap.testIssue580:2166-2178: iteration across seven
    # distinct high-48 keys
    vals = [3242766498713841665, 3492544636360507394, 3418218112527884289,
            3220956490660966402, 3495344165583036418, 3495023214002368514,
            3485108231289675778]
    rb = _rb64(*vals)
    assert sorted(v for v in rb) == sorted(vals)
    assert rb.cardinality == 7


def test_issue619_repeated_andnot():
    # TestRoaring64Bitmap.testIssue619:2265-2283: repeated add/andNot
    # cycles must converge, not lose members
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    cleaner_vals = [140664568792144]
    addr_vals = [140662937752432]
    address_space = Roaring64Bitmap()
    cleaner = Roaring64Bitmap.bitmap_of(*cleaner_vals)
    for iteration in range(34):
        for v in addr_vals:
            address_space.add(v)
        for v in cleaner_vals:
            address_space.add(v)
        if iteration == 33:
            break
        address_space.iandnot(cleaner)
    assert address_space.int_cardinality == 2


def test_with_yourself_64():
    # TestRoaring64Bitmap.testWithYourself:2152-2163: self-ops
    vals = list(range(1, 11))
    b1 = _rb64(*vals)
    b1.run_optimize()
    b1.ior(b1)
    assert b1 == _rb64(*vals)
    b1.ixor(b1)
    assert b1.is_empty()
    b1 = _rb64(*vals)
    b1.iand(b1)
    assert b1 == _rb64(*vals)
    b1.iandnot(b1)
    assert b1.is_empty()


# ------------------------------------------------- orNot truncation suite
# OrNotTruncationTest.java:17-63: a's members AT/ABOVE range_end must
# survive orNot regardless of the other operand's container-kind mix.

def _truncation_others():
    yield RoaringBitmap()
    yield RoaringBitmap.bitmap_of(2)
    yield RoaringBitmap.bitmap_of(2, 3, 4)
    b = RoaringBitmap(); b.add_range(2, 5); yield b
    b = RoaringBitmap(); b.add_range(3, 5); yield b
    b = RoaringBitmap(); b.add_range(1, 10); b.remove_range(2, 10); yield b
    yield RoaringBitmap.from_values(np.arange(7, dtype=np.uint32))
    for seed in (0, 1):
        yield _mixed_container_bitmap(seed)
    shifted = _mixed_container_bitmap(2).add_offset(1 << 16)
    yield shifted  # kinds starting at chunk 1, like withArrayAt(1) etc.


def test_ornot_does_not_truncate():
    from roaringbitmap_tpu.core.bitmap import or_not

    for other in _truncation_others():
        one = RoaringBitmap.bitmap_of(0, 10)
        got = or_not(one, other, 7)
        assert got.contains(10), "orNot truncated a member above range_end"
        assert got.contains(0)


# ------------------------------------- interval intersection/containment
# RoaringBitmapIntervalIntersectionTest.java: intersects(min, sup) and
# contains(min, sup) must agree with the materialized-range oracle across
# container-kind mixes and the 2^31 sign boundary.

def _interval_cases():
    yield RoaringBitmap.bitmap_of(1, 2, 3), 0, 1 << 16
    yield RoaringBitmap.bitmap_of((1 << 31) | (1 << 30)), 0, 1 << 16
    yield RoaringBitmap.bitmap_of((1 << 31) | (1 << 30)), 0, 256
    yield RoaringBitmap.bitmap_of(1, (1 << 31) | (1 << 30)), 0, 256
    yield RoaringBitmap.bitmap_of(1, 1 << 16, (1 << 31) | (1 << 30)), 0, 1 << 32
    m = _mixed_container_bitmap(3)
    m.add_range(70000, 150000)
    yield m, 70000, 150000
    yield m, 71000, 140000
    yield _mixed_container_bitmap(4), 67000, 150000
    big = _mixed_container_bitmap(5)
    big.add_many(((200 << 16) + np.arange(0, 60000, 3)).astype(np.uint32))
    yield big, 199 << 16, (200 << 16) + (1 << 14)


@pytest.fixture(scope="module")
def interval_cases():
    return list(_interval_cases())


@pytest.mark.parametrize("case", range(len(list(_interval_cases()))))
def test_interval_intersects_and_contains(interval_cases, case):
    bitmap, lo, hi = interval_cases[case]
    rng_bm = RoaringBitmap.from_range(lo, hi)
    assert bitmap.intersects_range(lo, hi) == bitmap.intersects(rng_bm)
    want_contains = (not rng_bm.is_empty()) and rng_bm.is_subset_of(bitmap)
    assert bitmap.contains_range(lo, hi) == want_contains
    assert rng_bm.is_empty() or rng_bm.contains_range(lo, hi)
    if bitmap.contains_range(lo, hi) and lo < hi:
        assert bitmap.intersects_range(lo, hi)


# ------------------------------------------------------ subset param matrix
# RoaringBitmapSubsetTest.java:15-140: contains(RoaringBitmap) across every
# container-kind pairing, verified against the Python-set oracle.

def _subset_cases():
    def rng_set(lo, hi):  # closed range like ContiguousSet
        return np.arange(lo, hi + 1, dtype=np.uint32)

    div4_15 = np.arange(4, (1 << 15) + 1, 4, dtype=np.uint32)
    div4_16 = np.arange(4, (1 << 16) + 1, 4, dtype=np.uint32)
    a = np.array
    return [
        (a([1, 2, 3, 4]), a([2, 3])),                 # array vs array
        (a([1, 2, 3, 4]), a([], np.uint32)),          # array vs empty
        (a([1, 2, 3, 4]), a([1, 2, 3, 4])),           # identical arrays
        (a([10, 12, 14, 15]), a([1, 2, 3, 4])),       # disjoint arrays
        (a([10, 12, 14]), a([1, 2, 3, 4])),           # card mismatch
        (rng_set(1, 1 << 8), a([1, 2, 3, 4])),        # run vs array subset
        (rng_set(1, 1 << 16), a([1, 2, 3, 4])),
        (rng_set(1, 1 << 16), a([], np.uint32)),      # run vs empty
        (rng_set(1, 1 << 16), rng_set(1, 1 << 16)),   # identical runs
        (rng_set(1, 1 << 20), rng_set(1, 1 << 20)),   # identical 2-cont runs
        (rng_set(1, 1 << 16), a([(1 << 16) + i for i in (1, 2, 3, 4)])),
        (rng_set(3, 1 << 16), a([1, 2])),
        (rng_set(1, 1 << 8), rng_set(1 << 4, 1 << 12)),  # run/run shift
        (rng_set(1, 1 << 20), a([1, 1 << 8])),
        (rng_set(1, 1 << 20), a([1 << 6, 1 << 26])),
        (a([1, 1 << 16]), rng_set(0, 1 << 20)),
        (div4_15, a([4, 8])),                         # bitmap vs array
        (div4_16, div4_15),                           # bitmap card mismatch
        (div4_15, a([], np.uint32)),                  # bitmap vs empty
        (div4_15, div4_15),                           # identical bitmaps
        (a([3, 7]), div4_15),                         # array vs bitmap
    ]


@pytest.mark.parametrize("case", range(len(_subset_cases())))
def test_subset_param_matrix(subset_cases, case):
    sup_v, sub_v = subset_cases[case]
    superset = RoaringBitmap.from_values(np.asarray(sup_v, dtype=np.uint32))
    superset.run_optimize()
    subset = RoaringBitmap.from_values(np.asarray(sub_v, dtype=np.uint32))
    subset.run_optimize()  # run containers on the SUBSET side too
    want = set(np.asarray(sub_v).tolist()) <= set(np.asarray(sup_v).tolist())
    assert subset.is_subset_of(superset) == want
    # and symmetric probes for free
    assert superset.is_subset_of(superset)
    assert RoaringBitmap().is_subset_of(superset)


@pytest.fixture(scope="module")
def subset_cases():
    return _subset_cases()


def test_pickle_roundtrip_all_classes(rng):
    """KryoTest analog: every serializable class round-trips through
    pickle (the reference round-trips RoaringBitmap/Roaring64NavigableMap
    through Kryo, KryoTest.java)."""
    import pickle

    from roaringbitmap_tpu import (Roaring64Bitmap, Roaring64NavigableMap)
    from roaringbitmap_tpu.core.fastrank import FastRankRoaringBitmap

    rb = _mixed_container_bitmap(6)
    rb.run_optimize()
    assert pickle.loads(pickle.dumps(rb)) == rb
    fr = FastRankRoaringBitmap(rb.keys, rb.containers)
    back = pickle.loads(pickle.dumps(fr))
    assert back == fr and isinstance(back, FastRankRoaringBitmap)
    v = rng.integers(0, 1 << 44, 3000, dtype=np.uint64)
    r64 = Roaring64Bitmap.from_values(v)
    assert pickle.loads(pickle.dumps(r64)) == r64
    nm = Roaring64NavigableMap.from_values(v, signed_longs=True)
    back = pickle.loads(pickle.dumps(nm))
    assert back == nm and back.signed_longs


def test_batch_iterator_clone_independence(rng):
    """CloneBatchIteratorTest.java: a cloned batch iterator advances
    independently of its source, from any mid-iteration position, and the
    same holds for the value-iterator flyweights."""
    vals = np.concatenate([np.array([1, 10, 20, 65560, 70000], np.uint32),
                           rng.integers(0, 1 << 22, 20000).astype(np.uint32)])
    rb = RoaringBitmap.from_values(vals)
    arr = rb.to_array()
    it1 = rb.get_batch_iterator(7)
    consumed = [it1.next_batch() for _ in range(3)]
    it2 = it1.clone()
    rest1 = np.concatenate(list(it1)) if it1.has_next() else np.empty(0)
    rest2 = np.concatenate(list(it2)) if it2.has_next() else np.empty(0)
    np.testing.assert_array_equal(rest1, rest2)
    np.testing.assert_array_equal(
        np.concatenate(consumed + [rest1]), arr)
    # clone after seek keeps the seek position
    it3 = rb.get_batch_iterator(16)
    it3.advance_if_needed(int(arr[arr.size // 2]))
    it4 = it3.clone()
    np.testing.assert_array_equal(np.concatenate(list(it3)),
                                  np.concatenate(list(it4)))
    # reverse flyweight clone
    rit = rb.get_reverse_int_iterator()
    for _ in range(5):
        rit.next()
    rit2 = rit.clone()
    assert list(rit) == list(rit2)


# ------------------------------------------------------- range op sweeps
# TestRange.java:569-760: exhaustive small-range sweeps where range ops
# must equal the point-op fold, across boundary alignments.

def test_clear_ranges_sweep():
    # testClearRanges:569-584
    N = 16
    for end in range(1, N):
        for start in range(end):
            a = RoaringBitmap.from_range(0, N)
            for k in range(start, end):
                a.remove(k)
            b = RoaringBitmap.from_range(0, N)
            b.remove_range(start, end)
            assert a == b, (start, end)


def test_flip_ranges_sweep():
    # testFlipRanges:587-601 (N reduced: per-point flip is the slow oracle)
    N = 64
    for end in range(1, N):
        for start in range(end):
            a = RoaringBitmap()
            for k in range(start, end):
                a.flip_range(k, k + 1)
            b = RoaringBitmap()
            b.flip_range(start, end)
            assert b.cardinality == end - start
            assert a == b, (start, end)


def test_set_ranges_sweep():
    # testSetRanges:706-719 — point-add oracle at small N, then the full
    # N=256 sweep (covering 64-bit word boundary crossings) against the
    # independent bulk-construction path
    for end in range(1, 16):
        for start in range(end):
            a = RoaringBitmap()
            for k in range(start, end):
                a.add(k)
            b = RoaringBitmap()
            b.add_range(start, end)
            assert a == b, (start, end)
    N = 256
    for end in range(1, N):
        for start in range(end):
            b = RoaringBitmap()
            b.add_range(start, end)
            want = RoaringBitmap.from_values(
                np.arange(start, end, dtype=np.uint32))
            assert b == want, (start, end)


def test_range_removal_idempotent():
    # testRangeRemoval:604-617
    bm = RoaringBitmap()
    bm.add(1)
    bm.run_optimize()
    bm.remove_run_compression()
    assert bm.cardinality == 1 and bm.contains(1)
    bm.remove_range(0, 1)   # no-op
    assert bm.cardinality == 1
    bm.remove_range(1, 2)
    bm.remove_range(1, 2)   # second removal of the same range: no-op
    assert bm.is_empty()


# ------------------------------------------------------ orNot numbered cases
# TestRoaringBitmapOrNot.java:26-380 — the deterministic orNot regressions
# (the fuzz model covers the bulk; these pin the exact shapes that broke).

def _ornot(a: RoaringBitmap, b: RoaringBitmap, end: int) -> RoaringBitmap:
    from roaringbitmap_tpu.core.bitmap import or_not
    return or_not(a, b, end)


def test_ornot_numbered_cases():
    # orNot1: complement fills to a dense prefix
    rb = RoaringBitmap.bitmap_of(2, 1, 1 << 16, 2 << 16, 3 << 16)
    rb2 = RoaringBitmap.bitmap_of(1 << 16, 3 << 16)
    got = _ornot(rb, rb2, (4 << 16) - 1)
    assert got.cardinality == (4 << 16) - 1
    np.testing.assert_array_equal(
        got.to_array(), np.arange((4 << 16) - 1, dtype=np.uint32))
    # orNot2: the only excluded position is b's single member
    rb = RoaringBitmap.bitmap_of(0, 1 << 16, 3 << 16)
    rb2 = RoaringBitmap.bitmap_of((4 << 16) - 1)
    got = _ornot(rb, rb2, 4 << 16)
    assert got.cardinality == (4 << 16) - 1
    np.testing.assert_array_equal(
        got.to_array(), np.arange((4 << 16) - 1, dtype=np.uint32))
    # orNot10: range_end below b's only member; a's last survives
    got = _ornot(RoaringBitmap.bitmap_of(5), RoaringBitmap.bitmap_of(10), 6)
    assert got.last() == 5
    # orNot11: extreme high chunks, sparse b far below range_end
    hi = 65535 * 65536 + 65523
    got = _ornot(RoaringBitmap.bitmap_of(hi),
                 RoaringBitmap.bitmap_of(65493 * 65536 + 65520), hi + 1)
    assert got.last() == hi


def test_ornot_against_full_bitmap():
    # orNotAgainstFullBitmap / NonEmpty / Static variants:345-380
    full = RoaringBitmap.from_range(0, 0x40000)
    assert _ornot(RoaringBitmap(), full, 0x30000).is_empty()
    rb = RoaringBitmap.bitmap_of(1, 0x10001, 0x20001)
    assert _ornot(rb, full, 0x30000) == rb


# ------------------------------------------------------- rank iterator sweep
# TestRankIterator.java:38-79: peekNextRank must equal bitmap.rank(next)
# at every position, both stepping singly and seeking by varied strides.

@pytest.mark.parametrize("advance", [0, 1, 3, 5, 7, 11, 131, 65537])
def test_rank_iterator_advance_sweep(advance):
    from roaringbitmap_tpu.core.iterators import PeekableIntRankIterator

    rb = _mixed_container_bitmap(8)
    # the withFull variant: a dense run spanning the chunk-0/1 boundary
    # (reference uses 262144; 70k keeps the per-position Python sweep fast
    # while still crossing container boundaries mid-iteration), plus
    # members at the top of the universe so the overflow guard below is
    # genuinely reachable
    rb.add_range(0, 70000)
    rb.add_many(np.array([0xFFFFFFFE, 0xFFFFFFFF], dtype=np.uint32))
    it = PeekableIntRankIterator(rb)
    if advance == 0:
        n = 0
        while it.has_next():
            n += 1
            assert it.peek_next_rank() == n
            it.next()
        assert n == rb.cardinality
    else:
        while it.has_next():
            bit = it.peek_next()
            assert it.peek_next_rank() == rb.rank(bit)
            if bit + advance < 0xFFFFFFFF:
                it.advance_if_needed(bit + advance)
            else:
                break
