"""Always-on serving loop acceptance (ISSUE 10).

Pins:
- served results bit-exact vs each set's sequential reference across
  query shapes (BatchQuery AND ExprQuery, both forms), tenants, and
  engines (multiset + mesh-sharded) — one admission/shed/fairness path;
- typed admission control: ``AdmissionRejected`` on queue caps and on
  HBM backpressure, and the backpressure PROPERTY — no dispatched
  pool's predicted footprint plus ledger-resident bytes exceeds the
  budget (asserted from the ``serving.dispatch`` trace spans);
- load shedding: expired/unmeetable requests shed with typed
  ``RequestShed`` (reason carried) or degrade bitmap -> cardinality per
  tenant policy — never silent;
- deadline propagation: the guard's per-dispatch deadline is clamped to
  the pool's remaining deadline (``GuardPolicy.for_remaining``), so a
  retry storm cannot outspend the query's budget — all on the fault
  clock, zero wall-clock flakiness;
- the overload ladder escalates (pool shrink -> field shed -> fair-share
  caps) and recovers symmetrically; weighted fairness orders assembly;
- the soak (slow lane): a >= 30 s simulated arrival stream under
  transient+oom+slow injection across >= 100 pools — bit-exact non-shed
  results, typed errors otherwise, HBM ledger back at baseline.
"""

import json

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.parallel import (BatchEngine, BatchQuery,
                                        MultiSetBatchEngine, expr)
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (AdmissionRejected, RequestShed,
                                       ServingLoop, ServingPolicy,
                                       ServingRequest, TenantPolicy)

#: no real sleeping, no outer deadline — per-dispatch deadlines come
#: from the serving loop's remaining-deadline clamp
NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)

#: far-future deadline for tests that pin parity, not timing (compile
#: walls on a cold engine are real seconds)
EASY_MS = 300_000.0


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    faults.reset_clock()
    yield
    obs.disable()
    obs.reset()
    faults.reset_clock()


@pytest.fixture(scope="module")
def tenant_bitmaps():
    rng = np.random.default_rng(0x5E11)
    out = []
    for s in range(3):
        out.append([RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 16, 700).astype(np.uint32)))
            for _ in range(6)])
    return out


@pytest.fixture(scope="module")
def engine(tenant_bitmaps):
    return MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps,
                                                layout="dense")


def _policy(**kw) -> ServingPolicy:
    kw.setdefault("guard", NOSLEEP)
    kw.setdefault("default_deadline_ms", EASY_MS)
    return ServingPolicy(**kw)


def _requests(n: int, n_sets: int = 3, seed: int = 0xA11,
              form_every: int = 3, expr_every: int = 7):
    """Mixed-shape stream: flat mixed-op queries, periodic bitmap forms,
    periodic expression DAGs — the one-wire-shape contract."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sid = int(rng.integers(n_sets))
        form = "bitmap" if i % form_every == 0 else "cardinality"
        if expr_every and i % expr_every == 3:
            e = expr.and_(expr.or_(0, 1), expr.not_(2))
            q = expr.ExprQuery(e, form=form)
        else:
            op = ("or", "and", "xor", "andnot")[int(rng.integers(4))]
            k = int(rng.integers(2, 5))
            q = BatchQuery(op, tuple(
                int(x) for x in rng.choice(6, size=k, replace=False)),
                form=form)
        out.append(ServingRequest(sid, q, tenant=f"t{sid}"))
    return out


def _assert_ticket_exact(engine, t):
    ref = engine._engines[t.request.set_id]._sequential_one(t.query)
    assert t.result.cardinality == ref.cardinality, t.request
    if t.query.form == "bitmap":
        assert t.result.bitmap == ref, t.request


# ------------------------------------------------------------ parity path

def test_serves_mixed_queries_bit_exact(engine):
    loop = ServingLoop(engine, _policy(pool_target=8))
    reqs = _requests(25)
    tickets = [loop.submit(r) for r in reqs]
    loop.pump()
    loop.drain()
    assert all(t.status == "done" for t in tickets)
    for t in tickets:
        _assert_ticket_exact(engine, t)
    assert loop.stats["served"] == len(reqs)
    assert loop.stats["pools"] >= 2
    # per-tenant SLO accounting reconciles with the served count
    snap = obs.snapshot()["counters"]
    attained = sum(r["value"]
                   for r in snap.get("rb_slo_attained_total", [])
                   if r["labels"].get("site") == "serving")
    missed = sum(r["value"]
                 for r in snap.get("rb_slo_missed_total", [])
                 if r["labels"].get("site") == "serving")
    assert attained + missed == len(reqs)


def test_expr_and_flat_share_one_path(engine):
    """Satellite: ExprQuery pools admit natively — the serving answer
    equals the direct engine call for the identical pooled queries."""
    loop = ServingLoop(engine, _policy(pool_target=6))
    reqs = [ServingRequest(1, expr.ExprQuery(
        expr.xor(expr.or_(0, 1), expr.and_(2, 3)), form="bitmap"),
        tenant="e"),
        ServingRequest(1, BatchQuery("or", (0, 1, 2), form="bitmap"),
                       tenant="e"),
        ServingRequest(0, expr.ExprQuery(
            expr.and_(expr.or_(1, 2), expr.not_(0))), tenant="e")]
    tickets = [loop.submit(r) for r in reqs]
    loop.drain()
    assert all(t.ok for t in tickets)
    direct = engine.execute([(r.set_id, (r.query,)) for r in reqs],
                            engine="auto")
    flat = [r for rows in direct for r in rows]
    for t, d in zip(tickets, flat):
        assert t.result.cardinality == d.cardinality
        if t.request.query.form == "bitmap":
            assert t.result.bitmap == d.bitmap


def test_sharded_engine_behind_the_same_loop(tenant_bitmaps, engine):
    """The loop pools into a ShardedBatchEngine unchanged (its dict
    footprint prediction rides the per-shard budget figure)."""
    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu.parallel import ShardedBatchEngine

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("rows", "data"))
    sharded = ShardedBatchEngine(engine._engines, mesh=mesh)
    loop = ServingLoop(sharded, _policy(pool_target=8))
    reqs = _requests(12, seed=0x5A)
    tickets = [loop.submit(r) for r in reqs]
    loop.drain()
    assert all(t.ok for t in tickets)
    for t in tickets:
        _assert_ticket_exact(engine, t)


# ------------------------------------------------------------- admission

def test_queue_cap_rejects_typed(engine):
    loop = ServingLoop(engine, _policy(max_queue=4))
    for i in range(4):
        loop.submit(ServingRequest(0, BatchQuery("or", (0, 1))))
    with pytest.raises(AdmissionRejected) as ei:
        loop.submit(ServingRequest(0, BatchQuery("or", (0, 1))))
    assert ei.value.reason == "queue_full"
    assert ei.value.context["queue_depth"] == 4
    snap = obs.snapshot()["counters"]
    rej = snap["rb_serving_admission_rejected_total"]
    assert rej[0]["labels"]["reason"] == "queue_full"
    assert loop.stats["rejected"] == 1
    assert loop._backlog() == 4          # the reject left no residue


def test_hbm_backpressure_rejects_and_pools_fit_budget(engine, tmp_path):
    """Acceptance: with a budget set, admission rejects typed once the
    ledger + pending footprint exceeds the headroom, and NO dispatched
    pool's predicted bytes + resident bytes exceed the budget —
    asserted from the serving.dispatch trace spans."""
    probe = ServingRequest(0, BatchQuery("or", (0, 1, 2)))
    scratch = ServingLoop(engine, _policy())
    per_req = scratch._request_bytes(probe)
    resident = obs_memory.LEDGER.resident_bytes()
    budget = int((resident + 3.2 * per_req) / 0.9)
    pol = _policy(guard=guard.GuardPolicy(
        backoff_base=0.0, sleep=lambda s: None, hbm_budget=budget),
        pool_target=8)
    loop = ServingLoop(engine, pol)
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    admitted, rejected = [], []
    for i in range(8):
        try:
            admitted.append(loop.submit(ServingRequest(
                0, BatchQuery("or", (0, 1, 2)), tenant="h")))
        except AdmissionRejected as e:
            rejected.append(e)
    loop.drain()
    obs.disable()
    assert admitted and rejected
    assert all(e.reason == "hbm" for e in rejected)
    assert all(e.context["budget_bytes"] == budget for e in rejected)
    served = [t for t in admitted if t.ok]
    assert served
    for t in served:
        _assert_ticket_exact(engine, t)
    spans = [json.loads(line) for line in open(path)]
    dispatches = [s for s in spans if s["name"] == "serving.dispatch"]
    assert dispatches
    for s in dispatches:
        tags = s["tags"]
        assert tags["predicted_bytes"] + tags["resident_bytes"] \
            <= tags["budget_bytes"], tags
    admits = [s for s in spans if s["name"] == "serving.admit"]
    outcomes = {s["tags"]["outcome"] for s in admits}
    assert outcomes == {"admitted", "rejected"}


# -------------------------------------------------------------- shedding

def test_expired_requests_shed_typed(engine):
    loop = ServingLoop(engine, _policy(pool_target=4))
    t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                   deadline_ms=50.0))
    faults.advance_clock(0.2)            # virtual: the deadline passed
    done = loop.pump(force=True)
    assert t in done and t.status == "shed"
    assert isinstance(t.error, RequestShed)
    assert t.error.reason == "expired"
    snap = obs.snapshot()["counters"]["rb_serving_shed_total"]
    assert any(r["labels"]["reason"] == "expired" for r in snap)


def test_unmeetable_drop_vs_degrade_per_tenant(engine):
    """A request whose remaining budget is under the predicted execute
    time sheds on a "drop" tenant and serves cardinality-only on a
    "degrade" tenant."""
    pol = _policy(pool_target=4, tenants={
        "d": TenantPolicy(on_deadline="drop"),
        "g": TenantPolicy(on_deadline="degrade")})
    loop = ServingLoop(engine, pol)
    loop._s_per_q = 0.2                  # calibrated: 200 ms per query
    td = loop.submit(ServingRequest(
        0, BatchQuery("or", (0, 1), form="bitmap"), tenant="d",
        deadline_ms=100.0))
    tg = loop.submit(ServingRequest(
        0, BatchQuery("or", (0, 1), form="bitmap"), tenant="g",
        deadline_ms=100.0))
    loop.pump(force=True)
    assert td.status == "shed" and td.error.reason == "deadline"
    assert tg.status == "done" and tg.degraded
    assert tg.result.bitmap is None      # cardinality-only, typed as such
    _assert_ticket_exact(engine, tg)     # ...and the count is exact
    snap = obs.snapshot()["counters"]
    assert any(r["labels"]["reason"] == "deadline"
               for r in snap["rb_serving_degraded_total"])


def test_shedding_disabled_serves_late(engine):
    loop = ServingLoop(engine, _policy(pool_target=4, shed=False))
    t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                   deadline_ms=10.0))
    faults.advance_clock(0.5)
    loop.pump(force=True)
    assert t.status == "done" and t.missed is True
    _assert_ticket_exact(engine, t)


def test_slow_fault_is_counted_against_slo(engine):
    """The `slow` kind at the serving site: injected pre-dispatch
    latency expires the request's SLO deterministically — served, but
    counted missed."""
    loop = ServingLoop(engine, _policy(pool_target=2, shed=False))
    with faults.inject("slow@serving=1.0:3"):
        t = loop.submit(ServingRequest(
            0, BatchQuery("or", (0, 1)), tenant="s",
            deadline_ms=faults.SLOW_LATENCY_S * 1e3 / 2))
        loop.pump(force=True)
    assert t.status == "done" and t.missed is True
    snap = obs.snapshot()["counters"]["rb_slo_missed_total"]
    assert any(r["labels"].get("tenant") == "s" for r in snap)


# ------------------------------------------------- deadline propagation

def test_for_remaining_clamps_both_knobs():
    base = guard.GuardPolicy(deadline=10.0, slo_deadline_ms=5000.0)
    p = base.for_remaining(0.25)
    assert p.deadline == 0.25 and p.slo_deadline_ms == 250.0
    # a tighter pre-existing knob survives
    tight = guard.GuardPolicy(deadline=0.1, slo_deadline_ms=50.0)
    p = tight.for_remaining(0.25)
    assert p.deadline == 0.1 and p.slo_deadline_ms == 50.0
    # unset knobs are derived, not left open
    p = guard.GuardPolicy().for_remaining(1.5)
    assert p.deadline == 1.5 and p.slo_deadline_ms == 1500.0


def test_guard_cannot_outspend_remaining_deadline(engine):
    """Satellite: slow+transient injection at the engine site — every
    attempt burns SLOW_LATENCY_S of virtual time and fails transient, so
    without the remaining-deadline clamp the ladder would spend
    attempts x rungs x 50 ms; with it the dispatch dies typed within the
    pool's remaining budget."""
    remaining_ms = 120.0
    loop = ServingLoop(engine, _policy(pool_target=2, shed=False))
    t0 = faults.clock()
    with faults.inject("slow@multiset=1.0,transient@multiset=1.0,"
                       "transient@batch_engine=1.0,"
                       "slow@batch_engine=1.0:5"):
        t = loop.submit(ServingRequest(
            0, BatchQuery("or", (0, 1)), deadline_ms=remaining_ms))
        loop.pump(force=True)
    spent = faults.clock() - t0
    assert t.status == "failed"
    assert isinstance(t.error, errors.RoaringRuntimeError)
    assert "deadline" in str(t.error)
    # 3 attempts x 4 rungs x 50 ms = 600 ms un-clamped; the clamp cuts
    # the ladder within remaining + one slow quantum
    assert spent <= remaining_ms / 1e3 + 2 * faults.SLOW_LATENCY_S, spent
    snap = obs.snapshot()["counters"]["rb_serving_pool_failures_total"]
    assert snap and snap[0]["value"] >= 1


# ------------------------------------------------------ overload ladder

def test_ladder_escalates_and_recovers_symmetrically(engine):
    pol = _policy(pool_target=4, escalate_after=1, recover_after=2,
                  overload_pressure=1.5)
    loop = ServingLoop(engine, pol)
    levels = []
    for _ in range(3):
        for r in _requests(16, seed=0xF00, expr_every=0):
            loop.submit(r)
        loop.pump(force=True)
        levels.append(loop.level)
    assert levels == [1, 2, 3]
    assert loop._pool_target() == 2      # level >= 1 halves the target
    # level 2+: bitmap requests served cardinality-only (field shedding)
    t = loop.submit(ServingRequest(
        0, BatchQuery("or", (0, 1), form="bitmap")))
    loop.pump(force=True)
    assert t.ok and t.degraded and t.result.bitmap is None
    gauge = obs.snapshot()["gauges"]["rb_serving_degrade_level"]
    assert gauge[0]["value"] == 3
    # symmetric recovery: calm pumps walk the ladder back down
    for want in (2, 1, 0):
        loop.pump()
        loop.pump()
        assert loop.level == want
    assert obs.snapshot()["gauges"]["rb_serving_degrade_level"][0][
        "value"] == 0


def test_weighted_fair_share(engine):
    """Stride scheduling: a weight-2 tenant gets twice the pool slots
    of a weight-1 tenant under contention."""
    pol = _policy(pool_target=6, tenants={
        "a": TenantPolicy(weight=2.0), "b": TenantPolicy(weight=1.0)})
    loop = ServingLoop(engine, pol)
    for i in range(12):
        loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                   tenant="a"))
        loop.submit(ServingRequest(1, BatchQuery("or", (0, 1)),
                                   tenant="b"))
    picked = loop._pick(6)
    by = {"a": 0, "b": 0}
    for t in picked:
        by[t.request.tenant] += 1
    assert by == {"a": 4, "b": 2}
    # level-3 caps make the share a hard per-pool bound
    loop.level = 3
    picked = loop._pick(6)
    caps = {"a": 0, "b": 0}
    for t in picked:
        caps[t.request.tenant] += 1
    assert caps == {"a": 4, "b": 2}


# -------------------------------------------------------------- tracing

def test_serving_span_vocabulary(engine, tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    loop = ServingLoop(engine, _policy(pool_target=4))
    for r in _requests(6, seed=2, expr_every=0):
        loop.submit(r)
    t = loop.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                   deadline_ms=1.0))
    faults.advance_clock(0.05)
    loop.drain()
    obs.disable()
    assert t.status == "shed"
    spans = [json.loads(line) for line in open(path)]
    names = {s["name"] for s in spans}
    assert {"serving.admit", "serving.assemble", "serving.dispatch",
            "serving.shed"} <= names
    sheds = [s for s in spans if s["name"] == "serving.shed"]
    assert all(s["tags"].get("reason") and s["tags"].get("tenant")
               for s in sheds)


def test_replay_backdates_late_arrivals(engine):
    loop = ServingLoop(engine, _policy(pool_target=4))
    reqs = _requests(8, seed=9, expr_every=0)
    tickets = loop.replay((i * 0.01, r) for i, r in enumerate(reqs))
    assert len(tickets) == len(reqs)
    assert all(t.status in ("done", "shed") for t in tickets)
    # arrival stamps follow the schedule: strictly increasing
    stamps = [t.enqueued_at for t in tickets]
    assert all(b > a for a, b in zip(stamps, stamps[1:]))


# ------------------------------------------------------------ soak (slow)

@pytest.mark.slow
def test_soak_sustained_stream_under_faults(tenant_bitmaps):
    """>= 30 s of simulated arrivals across >= 100 pools under
    transient+oom+slow injection: every non-shed query bit-exact, every
    shed/failed query typed, the HBM ledger back at its pre-soak
    baseline (no leak across pools)."""
    engine = MultiSetBatchEngine.from_bitmap_sets(tenant_bitmaps,
                                                  layout="dense")
    pol = _policy(pool_target=4, default_deadline_ms=120_000.0)
    loop = ServingLoop(engine, pol)
    # prime the compiled programs so the soak measures serving, not
    # compiles (the production warmup() story)
    for r in _requests(16, seed=1, expr_every=5):
        loop.submit(r)
    loop.drain()
    # flush cyclic garbage BEFORE both ledger readings: earlier tests'
    # engines sit in reference cycles, and a cyclic-GC pass firing
    # mid-soak would release THEIR registrations between the two
    # snapshots — a false leak signal about the serving loop
    import gc

    gc.collect()
    baseline = obs_memory.LEDGER.snapshot()

    n = 500
    gap = 0.08                           # 500 x 80 ms = 40 s simulated
    reqs = _requests(n, seed=0x50AC, expr_every=6)
    with faults.inject("transient=0.05,oom=0.05,slow=0.1:0x50AC"):
        tickets = loop.replay(
            (i * gap, r) for i, r in enumerate(reqs))
    assert len(tickets) == n
    assert loop.stats["pools"] >= 100
    statuses = {t.status for t in tickets}
    assert "queued" not in statuses and "rejected" not in statuses
    served = shed = 0
    for t in tickets:
        if t.status == "done":
            served += 1
            _assert_ticket_exact(engine, t)
        else:
            shed += 1
            assert isinstance(t.error, (RequestShed,
                                        errors.RoaringRuntimeError))
            assert str(t.error)          # typed AND descriptive
    assert served >= n * 0.5, (served, shed)
    gc.collect()
    assert obs_memory.LEDGER.snapshot() == baseline
