"""Fuzz/property tests (SURVEY §4.2) — algebraic identities and host/device
parity over RandomisedTestData-style region-mix inputs, mirroring
Fuzzer.java's invariance catalog.

Depth is env-tunable like the reference's `org.roaringbitmap.fuzz.iterations`
sysprop (Fuzzer.java:12): RB_FUZZ_ITERATIONS=10000 runs reference-depth;
the committed artifact of such a run lives at benchmarks/fuzz_r03.json
(produced by benchmarks/fuzz_run.py, which executes this same catalog)."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import (
    RoaringBitmap,
    and_,
    and_cardinality,
    andnot,
    or_,
    or_not,
    xor,
)
from roaringbitmap_tpu.parallel import aggregation, fast_aggregation
from roaringbitmap_tpu.utils import fuzz

#: per-property seeded iterations; 15 in the quick CI lane, 10k for the
#: reference-depth run (RB_FUZZ_ITERATIONS=10000)
IT = int(os.environ.get("RB_FUZZ_ITERATIONS", "15"))
#: device-path properties dispatch a compiled program per iteration, so the
#: deep run scales them down (still >= the reference's per-CI-shard depth)
IT_DEV = max(6, IT // 25)


def _arr(rb: RoaringBitmap) -> np.ndarray:
    return rb.to_array()


class TestAlgebraicInvariants:
    def test_roundtrip_serialization(self):
        fuzz.verify_invariance(
            lambda a: RoaringBitmap.deserialize(a.serialize()) == a,
            n_bitmaps=1, iterations=IT)

    def test_union_model(self):
        fuzz.verify_invariance(
            lambda a, b: np.array_equal(_arr(or_(a, b)),
                                        np.union1d(_arr(a), _arr(b))),
            iterations=IT)

    def test_intersection_model(self):
        fuzz.verify_invariance(
            lambda a, b: np.array_equal(_arr(and_(a, b)),
                                        np.intersect1d(_arr(a), _arr(b))),
            iterations=IT)

    def test_difference_model(self):
        fuzz.verify_invariance(
            lambda a, b: np.array_equal(_arr(andnot(a, b)),
                                        np.setdiff1d(_arr(a), _arr(b))),
            iterations=IT)

    def test_xor_model(self):
        fuzz.verify_invariance(
            lambda a, b: np.array_equal(_arr(xor(a, b)),
                                        np.setxor1d(_arr(a), _arr(b))),
            iterations=IT)

    def test_demorgan_via_ornot(self):
        """a | ~b over a bounded range, against a NumPy complement model."""
        def prop(a, b):
            end = 1 << 20
            comp = np.setdiff1d(np.arange(end, dtype=np.uint32), _arr(b))
            expect = np.union1d(_arr(a), comp)
            return np.array_equal(_arr(or_not(a, b, end)), expect)
        fuzz.verify_invariance(prop, iterations=max(5, IT // 3))

    def test_cardinality_inclusion_exclusion(self):
        fuzz.verify_invariance(
            lambda a, b: or_(a, b).cardinality
            == a.cardinality + b.cardinality - and_cardinality(a, b),
            iterations=IT)

    def test_rank_select_inverse(self):
        def prop(a):
            card = a.cardinality
            for j in range(0, card, max(1, card // 7)):
                if a.rank(a.select(j)) != j + 1:
                    return False
            return True
        fuzz.verify_invariance(prop, n_bitmaps=1, iterations=IT)

    def test_flip_involution(self):
        def prop(a):
            c = a.clone()
            c.containers = list(c.containers)
            c.flip_range(1 << 10, 1 << 21)
            c.flip_range(1 << 10, 1 << 21)
            return c == a
        fuzz.verify_invariance(prop, n_bitmaps=1, iterations=IT)

    def test_add_offset_model(self):
        """Container-granular shift == the value-array oracle, offset drawn
        from the straddling/aligned/negative/overflow mix each iteration
        (TestConcatenation invariants at fuzz depth)."""
        offsets = [1, -1, 20, 65535, 1 << 16, -(1 << 16), (1 << 16) + 3,
                   (1 << 31), -(1 << 31), (1 << 33)]
        state = {"i": 0}

        def prop(a):
            off = offsets[state["i"] % len(offsets)]
            state["i"] += 1
            want = _arr(a).astype(np.int64) + off
            want = want[(want >= 0) & (want <= 0xFFFFFFFF)]
            return np.array_equal(_arr(a.add_offset(off)).astype(np.int64),
                                  want)
        fuzz.verify_invariance(prop, n_bitmaps=1, iterations=IT)

    def test_inplace_delta_model(self):
        """O(delta) in-place merges == static algebra (the addN-contract
        rewrite must stay bit-identical for every kind mix)."""
        def prop(a, b):
            for op, fn in (("ior", or_), ("ixor", xor),
                           ("iandnot", andnot), ("iand", and_)):
                c = a.clone()
                getattr(c, op)(b)
                if c != fn(a, b):
                    return False
            return True
        fuzz.verify_invariance(prop, iterations=IT)


class TestDeviceParityFuzz:
    """jit-vs-host parity — the race-detector analog (SURVEY §5): device
    reductions must be bit-exact with the host fold regardless of order.
    Both engines fuzzed (pallas runs interpret-mode here; the compiled
    Mosaic path is covered by tests/test_on_tpu.py)."""

    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    def test_wide_or_parity(self, engine):
        def prop(*bitmaps):
            host = fast_aggregation.naive_or(*bitmaps)
            dev = aggregation.or_(list(bitmaps), engine=engine,
                                  fallback=False)
            return dev == host
        fuzz.verify_invariance(prop, n_bitmaps=4, iterations=IT_DEV,
                               max_keys=8)

    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    def test_wide_xor_parity(self, engine):
        def prop(*bitmaps):
            host = fast_aggregation.naive_xor(*bitmaps)
            dev = aggregation.xor(list(bitmaps), engine=engine,
                                  fallback=False)
            return dev == host
        fuzz.verify_invariance(prop, n_bitmaps=4, iterations=IT_DEV,
                               max_keys=8)

    def test_wide_and_parity(self):
        def prop(*bitmaps):
            host = fast_aggregation.naive_and(*bitmaps)
            dev = aggregation.and_(list(bitmaps), fallback=False)
            return dev == host
        fuzz.verify_invariance(prop, n_bitmaps=3, iterations=IT_DEV,
                               max_keys=8)

    def test_byte_path_ingest_parity(self):
        """Serialized blobs -> DeviceBitmapSet must equal the host fold —
        round-trips the full wire format THROUGH the stream-ingest guards
        over the region mix."""
        def prop(*bitmaps):
            host = fast_aggregation.naive_or(*bitmaps)
            ds = aggregation.DeviceBitmapSet([b.serialize() for b in bitmaps])
            return ds.aggregate("or", engine="xla") == host
        fuzz.verify_invariance(prop, n_bitmaps=3, iterations=IT_DEV,
                               max_keys=6)

    def test_pairwise_parity(self):
        def prop(a, b):
            got = aggregation.pairwise("and", [(a, b)], engine="xla")[0]
            return got == (a & b)
        fuzz.verify_invariance(prop, n_bitmaps=2, iterations=IT_DEV,
                               max_keys=6)


class TestStrategyEquivalence:
    """Every FastAggregation strategy returns the same set."""

    def test_or_strategies_agree(self):
        def prop(*bitmaps):
            bs = list(bitmaps)
            ref = fast_aggregation.naive_or(bs)
            return (fast_aggregation.priorityqueue_or(bs) == ref
                    and fast_aggregation.horizontal_or(bs, engine="xla") == ref
                    and fast_aggregation.or_(bs, engine="xla") == ref)
        fuzz.verify_invariance(prop, n_bitmaps=4, iterations=IT_DEV, max_keys=6)

    def test_xor_strategies_agree(self):
        def prop(*bitmaps):
            bs = list(bitmaps)
            ref = fast_aggregation.naive_xor(bs)
            return (fast_aggregation.priorityqueue_xor(bs) == ref
                    and fast_aggregation.horizontal_xor(bs, engine="xla") == ref)
        fuzz.verify_invariance(prop, n_bitmaps=4, iterations=IT_DEV, max_keys=6)

    def test_and_strategies_agree(self):
        def prop(*bitmaps):
            bs = list(bitmaps)
            ref = fast_aggregation.naive_and(bs)
            return (fast_aggregation.work_shy_and(bs) == ref
                    and fast_aggregation.and_(bs) == ref)
        fuzz.verify_invariance(prop, n_bitmaps=3, iterations=IT_DEV, max_keys=6)

    def test_cardinality_strategies(self):
        def prop(*bitmaps):
            bs = list(bitmaps)
            return (fast_aggregation.or_cardinality(bs)
                    == fast_aggregation.naive_or(bs).cardinality
                    and fast_aggregation.and_cardinality(bs)
                    == fast_aggregation.naive_and(bs).cardinality)
        fuzz.verify_invariance(prop, n_bitmaps=3, iterations=IT_DEV, max_keys=6)


class TestReporter:
    def test_failure_artifact_replays(self):
        with pytest.raises(AssertionError) as e:
            fuzz.verify_invariance(lambda a: a.cardinality < 0,
                                   n_bitmaps=1, iterations=1, seed=7)
        artifact = str(e.value)
        replayed = fuzz.replay(artifact)
        assert len(replayed) == 1
        assert replayed[0].cardinality > 0

    def test_crash_reported_with_inputs(self):
        def boom(a):
            raise RuntimeError("kaboom")
        with pytest.raises(AssertionError) as e:
            fuzz.verify_invariance(boom, n_bitmaps=1, iterations=1)
        assert "kaboom" in str(e.value)
        assert fuzz.replay(str(e.value))

    def test_seeded_reproducibility(self):
        rng1 = np.random.default_rng(42)
        rng2 = np.random.default_rng(42)
        assert fuzz.random_bitmap(rng1) == fuzz.random_bitmap(rng2)


class TestDecoderHardening:
    """Mutation corpus over the serialized format (robustness satellite):
    the parser either accepts or raises InvalidRoaringFormat — raw numpy/
    struct errors escaping the decode are the bug class this hunts."""

    def test_mutation_corpus_never_leaks_raw_errors(self):
        rejected = fuzz.verify_decoder_hardening(iterations=200)
        assert rejected > 0          # the corpus does produce malformed blobs

    def test_every_mutation_kind_covered(self):
        rng = np.random.default_rng(0)
        rb = fuzz.random_bitmap(rng)
        blob = rb.serialize()
        from roaringbitmap_tpu import InvalidRoaringFormat, RoaringBitmap
        for kind in fuzz.MUTATION_KINDS:
            m = fuzz.mutate_serialized(np.random.default_rng(3), blob, kind)
            try:
                RoaringBitmap.deserialize(m)
            except InvalidRoaringFormat:
                pass                 # typed rejection is a pass

    def test_mutations_are_deterministic(self):
        rng = np.random.default_rng(5)
        blob = fuzz.random_bitmap(rng).serialize()
        a = fuzz.mutate_serialized(np.random.default_rng(9), blob)
        b = fuzz.mutate_serialized(np.random.default_rng(9), blob)
        assert a == b
