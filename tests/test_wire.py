"""Binary wire front door acceptance (ISSUE 20).

Pins:
- a REAL second OS process (``wire.bootstrap`` subprocess) serves mixed
  flat/expression/analytics traffic over TCP bit-exactly vs the local
  reference engine built from the same seeded dataset;
- pipelined submission completes OUT OF ORDER by req_id — the client's
  observed completion order is the server's completion order, not the
  submission order;
- every overload outcome is a typed wire error frame on the LIVE
  connection: admission rejections, backpressure past the in-flight
  cap, auth/tenant refusals, malformed frames (CorruptInput) — never a
  silent drop, never a raw socket/struct escape;
- ``wire@{conn_drop,slow_peer,garbage}`` fault rules die as typed
  ``PeerClosed`` / ``CorruptInput`` / fault-clock latency;
- live migration over the wire lands a bit-exact twin (per-source CRC
  pin) with catch-up deltas from the dual-write window;
- the slow-lane soak replays the Zipf/diurnal generator over the wire
  under fault injection with typed-only failures.
"""

import json
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from roaringbitmap_tpu import obs
from roaringbitmap_tpu.mutation import delta as mut_delta
from roaringbitmap_tpu.parallel import (MultiSetBatchEngine, expr,
                                        podmesh)
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (PodFrontDoor, ServingLoop,
                                       ServingPolicy, ServingRequest,
                                       Ticket, migrate_tenant, replay)
from roaringbitmap_tpu.wire import (WireClient, WireServer,
                                    migrate_tenant_wire)
from roaringbitmap_tpu.wire import protocol as wp

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)
EASY_MS = 300_000.0

PROFILE = replay.ReplayProfile(sets=2, sources=6, tenants=4,
                               density=600, users=1 << 16, seed=11)


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    faults.reset_clock()
    yield
    obs.disable()
    obs.reset()
    faults.reset_clock()


@pytest.fixture(scope="module")
def dataset():
    return replay.build_dataset(PROFILE)


def _sets(dataset):
    sets = [DeviceBitmapSet(b, layout="dense") for b in dataset[0]]
    replay.attach_columns(sets, PROFILE, dataset[1])
    return sets


def _loop(dataset, **kw):
    kw.setdefault("pool_target", 4)
    kw.setdefault("guard", NOSLEEP)
    kw.setdefault("default_deadline_ms", EASY_MS)
    return ServingLoop(MultiSetBatchEngine(_sets(dataset)),
                       ServingPolicy(**kw))


def _requests(n, seed=5, n_sets=2, n_sources=6):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sid = int(rng.integers(n_sets))
        form = "bitmap" if i % 3 == 0 else "cardinality"
        if i % 5 == 2:
            q = expr.ExprQuery(
                expr.and_(expr.or_(0, 1), expr.not_(2)), form=form)
        elif i % 5 == 4:
            q = expr.ExprQuery(expr.sum_("v", expr.or_(0, 1)),
                               form="cardinality")
        else:
            op = ("or", "and", "xor", "andnot")[int(rng.integers(4))]
            k = int(rng.integers(2, 5))
            q = BatchQuery(op, tuple(int(x) for x in rng.choice(
                n_sources, size=k, replace=False)), form=form)
        out.append(ServingRequest(sid, q, tenant=f"t{sid}"))
    return out


def _assert_wire_exact(engine, req, res):
    ref = engine._engines[req.set_id]._sequential_result(req.query)
    assert res.cardinality == ref.cardinality, req
    if req.query.form == "bitmap" and not res.degraded:
        assert res.bitmap == ref.bitmap, req
    if ref.value is not None:
        assert res.value == ref.value, req


# ----------------------------------------------------- loopback data plane

def test_hello_welcome_and_ping(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        assert cl.server["version"] == wp.WIRE_VERSION
        assert cl.server["n_sets"] == 2
        cl.ping()
        cl.close()


def test_loopback_parity_all_query_shapes(dataset):
    """Flat, expression, and analytics queries (both forms) served over
    TCP are bit-exact vs the sequential per-set reference."""
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        reqs = _requests(20)
        tickets = cl.submit_many(reqs)
        for t, r in zip(tickets, reqs):
            _assert_wire_exact(loop._engine, r, t.value(timeout=60))
        cl.close()


def test_bad_magic_is_typed_hello_mismatch(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        s = socket.create_connection(srv.address, timeout=5)
        s.sendall(b"NOTMAGIC" + wp.encode_frame(
            wp.T_HELLO, 0, {"version": wp.WIRE_VERSION}))
        ftype, req_id, h, _ = wp.read_frame(s)
        assert ftype == wp.T_ERROR and h["code"] == "hello_mismatch"
        s.close()


def test_version_skew_is_typed(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        s = socket.create_connection(srv.address, timeout=5)
        s.sendall(wp.WIRE_MAGIC + wp.encode_frame(
            wp.T_HELLO, 0, {"version": 999}))
        ftype, _, h, _ = wp.read_frame(s)
        assert ftype == wp.T_ERROR and h["code"] == "hello_mismatch"
        s.close()


def test_garbage_inbound_dies_as_corrupt_input(dataset):
    """A garbled inbound frame loses framing sync: the server answers
    ONE connection-level typed CorruptInput frame, then closes — no raw
    struct/socket error anywhere."""
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        t = cl._reserve()                     # in flight when sync dies
        good = wp.encode_frame(wp.T_PING, 99, {})
        with cl._wlock:
            cl._sock.sendall(wp.garble(good))
        t.wait(10)
        assert t.status == "failed"
        assert isinstance(t.error, errors.CorruptInput)
        cl.close()


# ----------------------------------------------- pipelining + out of order

class _LifoTarget:
    """Completes every drained batch in REVERSE submission order — a
    deterministic out-of-order completer for pipelining pins."""

    n_sets = 1

    def __init__(self):
        # reentrant by the target contract (ServingLoop and
        # PodFrontDoor both expose an RLock): the server nests a
        # burst-wide acquisition around the per-submit one
        self._lock = threading.RLock()
        self._listeners = []
        self._q = []

    def add_completion_listener(self, fn):
        self._listeners.append(fn)

    def remove_completion_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def submit(self, request, arrival=None):
        t = Ticket(request=request)
        self._q.append(t)
        return t

    def backlog(self):
        return len(self._q)

    def pump(self, force=False):
        return []

    def drain(self):
        out, self._q = list(reversed(self._q)), []
        for t in out:
            t.status = "done"
            from roaringbitmap_tpu.parallel.batch_engine import BatchResult
            t.result = BatchResult(cardinality=t.request.set_id,
                                   bitmap=None, value=None)
            t.missed = False
        for fn in list(self._listeners):
            fn(out)
        return out


def test_pipelined_completion_is_out_of_order():
    """N pipelined submits on ONE connection complete in the server's
    order (here: deterministically reversed), resolved by req_id."""
    with WireServer(_LifoTarget(), coalesce_s=0.05) as srv:
        cl = WireClient(srv.address)
        reqs = [ServingRequest(0, BatchQuery("or", (0, 1)),
                               tenant="t") for _ in range(8)]
        tickets = cl.submit_many(reqs)
        for t in tickets:
            t.wait(30)
        assert all(t.ok for t in tickets)
        ids = [t.req_id for t in tickets]
        assert cl.completion_order == list(reversed(ids))
        cl.close()


class _StuckTarget(_LifoTarget):
    """Accepts submits but never completes them — the backpressure
    window fills and stays full."""

    def drain(self):
        return []


def test_backpressure_past_inflight_cap_is_typed():
    with WireServer(_StuckTarget(), max_inflight=3) as srv:
        cl = WireClient(srv.address)
        reqs = [ServingRequest(0, BatchQuery("or", (0, 1)), tenant="t")
                for _ in range(6)]
        tickets = cl.submit_many(reqs)
        # frames process in order: the first 3 admit (and sit in the
        # stuck target forever), the overflow 3 answer typed at once
        bp = [t for t in tickets[3:] if t._event.wait(10)]
        assert len(bp) == 3, [t.status for t in tickets]
        for t in bp:
            assert t.status == "failed"
            assert isinstance(t.error, errors.WireBackpressure)
            assert t.error.retryable and t.error.context["cap"] == 3
        assert all(t.status == "pending" for t in tickets[:3])
        # the connection survived: a ping still round-trips
        cl.ping()
        cl.close()


def test_admission_rejection_rides_the_wire_typed(dataset):
    """A full tenant queue rejects typed over the wire; the connection
    keeps serving afterwards."""
    loop = _loop(dataset, max_queue=2, pool_target=64)
    with WireServer(loop, coalesce_s=0.05) as srv:
        cl = WireClient(srv.address)
        q = BatchQuery("or", (0, 1, 2))
        reqs = [ServingRequest(0, q, tenant="t0") for _ in range(10)]
        tickets = cl.submit_many(reqs)
        for t in tickets:
            t.wait(60)
        rejected = [t for t in tickets if t.status == "failed"]
        assert rejected, "queue cap 2 never rejected out of 10"
        for t in rejected:
            from roaringbitmap_tpu.serving import AdmissionRejected
            assert isinstance(t.error, AdmissionRejected)
            assert t.error.reason == "queue_full"
        done = [t for t in tickets if t.ok]
        assert done and len(done) + len(rejected) == 10  # zero silent
        cl.ping()
        cl.close()


# ----------------------------------------------------------- auth boundary

def test_unknown_token_refused_before_any_submit(dataset):
    loop = _loop(dataset)
    with WireServer(loop, auth={"good": ["t0"]}) as srv:
        with pytest.raises(errors.AuthRejected):
            WireClient(srv.address, token="evil")
        with pytest.raises(errors.AuthRejected):
            WireClient(srv.address)          # missing token entirely
        assert loop.stats["admitted"] == 0   # nothing reached the loop


def test_tenant_grant_enforced_per_request(dataset):
    loop = _loop(dataset)
    with WireServer(loop, auth={"tok": ["t0"], "root": ["*"]}) as srv:
        cl = WireClient(srv.address, token="tok")
        q = BatchQuery("or", (0, 1, 2))
        ok = cl.submit(ServingRequest(0, q, tenant="t0"))
        bad = cl.submit(ServingRequest(0, q, tenant="t1"))
        assert ok.value(60).cardinality >= 0
        with pytest.raises(errors.AuthRejected) as ei:
            bad.value(60)
        assert ei.value.context["tenant"] == "t1"
        cl.ping()                            # connection still live
        cl.close()
        root = WireClient(srv.address, token="root")
        assert root.call(
            ServingRequest(1, q, tenant="t1"), 60).cardinality >= 0
        root.close()


# --------------------------------------------------------- fault injection

def test_wire_fault_conn_drop_fails_typed(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        with faults.inject("wire@conn_drop=1.0:1"):
            with pytest.raises(errors.PeerClosed):
                cl.submit(ServingRequest(
                    0, BatchQuery("or", (0, 1)), tenant="t0"))
        cl.close()


def test_wire_fault_garbage_on_response_fails_typed(dataset):
    """Server-side garbled response frame: the client's reader loses
    sync and fails everything in flight with CorruptInput — typed, not
    a struct.error."""
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        t = cl.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                     tenant="t0"))
        with faults.inject("wire@garbage=1.0:1"):
            with pytest.raises(errors.CorruptInput):
                t.value(30)
        cl.close()


def test_wire_fault_slow_peer_advances_fault_clock(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        t0 = faults.clock()
        with faults.inject("wire@slow_peer=1.0:1"):
            t = cl.submit(ServingRequest(0, BatchQuery("or", (0, 1)),
                                         tenant="t0"))
            t.value(60)
        assert faults.clock() - t0 >= faults.SLOW_LATENCY_S
        cl.close()


def test_wire_rule_requires_scope():
    with pytest.raises(ValueError):
        faults.FaultPlan.from_spec("wire=1.0:1")
    with pytest.raises(ValueError):
        faults.FaultPlan.from_spec("wire@bogus=1.0:1")


# ------------------------------------------------------------ remote delta

def test_delta_over_wire_then_query_bit_exact(dataset):
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        q = BatchQuery("or", (0, 1), form="bitmap")
        before = cl.call(ServingRequest(0, q, tenant="t0"), 60)
        vals = np.array([1_000_001, 1_000_002], np.uint32)
        report = cl.apply_delta(0, adds={0: vals})
        assert report and isinstance(report, dict)
        after = cl.call(ServingRequest(0, q, tenant="t0"), 60)
        ref = before.bitmap.to_array()
        want = np.union1d(ref, vals)
        assert np.array_equal(after.bitmap.to_array(), want)
        cl.close()


def test_delta_repack_serialized_with_dispatch(dataset):
    """A structural delta (new container key -> escalated repack, which
    FREES the set's old device buffers) racing a pipelined query pool
    must not lose tickets: the wire reader serializes the apply with
    the loop's pump lock, so every in-flight query reaches a terminal
    status and post-delta queries are bit-exact.  Regression: the
    unserialized apply let a mid-dispatch pool die on the freed
    buffers ('buffer deleted', unclassified) — a silent drop."""
    loop = _loop(dataset, pool_target=8)
    with WireServer(loop, coalesce_s=0.02) as srv:
        cl = WireClient(srv.address)
        for round_ in range(4):
            reqs = _requests(10, seed=60 + round_)
            tickets = cl.submit_many(reqs)
            # structural: values far above the build universe force a
            # fresh container while the pool above is still in flight
            base = 2_000_000 + 10_000 * round_
            report = cl.apply_delta(
                0, adds={0: np.arange(base, base + 64, dtype=np.uint32)},
                timeout=120)
            assert isinstance(report, dict)
            for t in tickets:
                assert t.wait(120), "ticket lost in the delta race"
                assert t.status in ("done", "failed")
                if t.status == "failed":
                    assert isinstance(t.error,
                                      errors.RoaringRuntimeError)
        # the connection survived and serves the post-delta image
        res = cl.call(ServingRequest(
            0, BatchQuery("or", (0, 1), form="bitmap"), tenant="t0"), 60)
        ref = loop._engine._engines[0]._sequential_result(
            BatchQuery("or", (0, 1), form="bitmap"))
        assert res.bitmap == ref.bitmap
        cl.close()


# -------------------------------------------------------- cross-process

def _spawn_bootstrap(*extra):
    proc = subprocess.Popen(
        [sys.executable, "-m", "roaringbitmap_tpu.wire.bootstrap",
         "--seed", str(PROFILE.seed), "--sets", str(PROFILE.sets),
         "--sources", str(PROFILE.sources),
         "--tenants", str(PROFILE.tenants),
         "--density", str(PROFILE.density),
         "--users", str(PROFILE.users), *extra],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    info = json.loads(proc.stdout.readline())
    return proc, (info["host"], info["port"])


def test_cross_process_submission_bit_exact(dataset):
    """THE acceptance pin: a separate OS process serves mixed traffic
    over TCP bit-exactly vs the local reference engine built from the
    same seeded dataset."""
    proc, addr = _spawn_bootstrap()
    try:
        reference = MultiSetBatchEngine(_sets(dataset))
        cl = WireClient(addr, timeout=120)
        reqs = _requests(24)
        tickets = cl.submit_many(reqs)
        for t, r in zip(tickets, reqs):
            _assert_wire_exact(reference, r, t.value(timeout=120))
        assert cl.stats["results"] == len(reqs)
        cl.close()
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=15) == 0


def test_cross_process_delta_convergence(dataset):
    """Deltas shipped over the wire mutate the remote process; the
    remote result converges bit-exactly with a local twin applying the
    same delta."""
    proc, addr = _spawn_bootstrap()
    try:
        sets = _sets(dataset)
        cl = WireClient(addr, timeout=120)
        vals = np.array([7, 77, 777], np.uint32)
        cl.apply_delta(1, adds={2: vals})
        sets[1].apply_delta({2: vals}, None)
        reference = MultiSetBatchEngine(sets)
        q = BatchQuery("or", (0, 2), form="bitmap")
        req = ServingRequest(1, q, tenant="t1")
        _assert_wire_exact(reference, req, cl.call(req, 120))
        cl.close()
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=15) == 0


# ------------------------------------------------------------- migration

def _front_door(dataset):
    sets = _sets(dataset)
    return PodFrontDoor(
        sets, pod=podmesh.PodMesh.simulate(2),
        policy=ServingPolicy(pool_target=4, guard=NOSLEEP,
                             default_deadline_ms=EASY_MS))


def test_wire_migration_bit_exact_with_catch_up(dataset):
    """migrate_tenant(via=client) ships snapshot + dual-write catch-up
    tail as frames; the destination's restored twin passes the per-
    source CRC pin, and the source keeps serving throughout."""
    fd = _front_door(dataset)
    dest_loop = _loop(dataset)
    with WireServer(dest_loop, name="dest") as srv:
        cl = WireClient(srv.address)

        def during(fd_):
            # traffic + mutation INSIDE the dual-write window
            t = fd_.submit(ServingRequest(
                1, BatchQuery("or", (0, 1)), tenant="t1"))
            fd_.apply_delta(1, {0: np.array([31337], np.uint32)}, None)
            fd_.drain()
            assert t.ok

        report = migrate_tenant(fd, 1, via=cl, tenant="mig-t1",
                                during=during)
        assert report["to"] == "wire"
        assert report["catch_up_records"] >= 1
        ds = srv.migrated["mig-t1"]
        src = mut_delta.host_bitmaps(fd._sets[1])
        got = mut_delta.host_bitmaps(ds)
        assert got == src                      # bit-exact twin
        assert 31337 in got[0]
        # source unaffected: still serving tenant 1
        t = fd.submit(ServingRequest(1, BatchQuery("or", (0, 1)),
                                     tenant="t1"))
        fd.drain()
        assert t.ok
        cl.close()


def test_wire_migration_cross_process(dataset):
    """Full two-process migration: snapshot + tail land in a bootstrap
    subprocess, CRC pin checked end to end."""
    proc, addr = _spawn_bootstrap()
    try:
        fd = _front_door(dataset)
        cl = WireClient(addr, timeout=120)
        report = migrate_tenant_wire(fd, 0, cl, tenant="xp-t0")
        assert report["bytes"] > 0
        assert report["source_crcs"]           # pin verified inside
        cl.close()
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=15) == 0


# ------------------------------------------------------------------- soak

@pytest.mark.slow
def test_soak_replay_over_wire_typed_only(dataset):
    """The Zipf/diurnal replay generator over a live wire under fault
    injection: every ticket resolves, every failure is typed, the
    connection-level fault (garbage) yields CorruptInput — zero raw
    escapes, zero silent drops."""
    profile = replay.ReplayProfile(sets=2, sources=6, tenants=6,
                                   density=600, users=1 << 16,
                                   requests=120, duration_s=1.0,
                                   seed=PROFILE.seed)
    events = replay.generate(profile)
    loop = _loop(dataset)
    with WireServer(loop) as srv:
        cl = WireClient(srv.address, timeout=120)
        with faults.inject("wire@garbage=0.02:7"):
            try:
                rep = replay.run_wire(cl, events, pace=False,
                                      timeout=120)
            except (errors.PeerClosed, errors.CorruptInput):
                rep = None                     # typed connection death
        if rep is not None:
            assert rep["typed_only"], rep
            assert (rep["done"] + rep["shed"] + rep["failed"]
                    + rep["rejected"]) == rep["queries"]
        cl.close()
