"""Persistent device-resident pool queue acceptance (ISSUE 16).

Pins:
- :class:`DescriptorRing` protocol properties: slot wraparound past
  capacity, full-ring and wedged-ring admission as TYPED
  ``RingBackpressure`` (never an overwrite, never silent), FIFO
  completion-stamp enforcement (an out-of-order stamp wedges), and the
  drain barrier (fault clock — a wedged or stalled ring is typed
  backpressure, not a hang);
- ``signature_id``: a closed mixed-radix enum over the SEALED lattice's
  dimension tuples — injective over the vocabulary, None outside it;
- every :class:`ResidentQueue` escape is typed with its reason
  (``inactive`` / ``backend`` / ``vocabulary`` / ``wedged``) and the
  serving loop demotes such pools to the one-shot dispatch path,
  bit-exact, with ``rb_serving_resident_demotions_total`` moved;
- the steady-state pin: >= 64 fused-analytics pools replayed through a
  resident serving loop move ``rb_serving_dispatches_total`` ZERO
  times (every pool ring-served), bit-exact vs the host BSI oracle.
"""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.analytics import BsiColumn
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.parallel import MultiSetBatchEngine, expr
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
from roaringbitmap_tpu.parallel.multiset import BatchGroup
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.runtime import lattice as rt_lattice
from roaringbitmap_tpu.serving import (DescriptorRing, ResidentEscape,
                                       ResidentQueue, RingBackpressure,
                                       ServingLoop, ServingPolicy,
                                       ServingRequest)
from roaringbitmap_tpu.serving.loop import replay_stream
from roaringbitmap_tpu.serving.resident import signature_id

NOSLEEP = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    faults.reset_clock()
    rt_lattice.deactivate()
    yield
    obs.disable()
    obs.reset()
    faults.reset_clock()
    rt_lattice.deactivate()


# --------------------------------------------------- ring protocol


def test_ring_wraparound_reuses_slots():
    ring = DescriptorRing(4)
    for i in range(11):                  # nearly 3 laps of capacity 4
        slot, seq = ring.push(i, payload=i)
        assert slot == i % 4 and seq == i + 1
        d = ring.pop()
        assert (d.slot, d.seq, d.sig_id, d.payload) == (slot, seq, i, i)
        ring.complete(slot, seq)
        assert ring.poll(seq)
    assert ring.depth() == 0 and ring.in_flight() == 0
    assert not ring.wedged


def test_ring_capacity_rejects_typed():
    ring = DescriptorRing(4)
    for i in range(4):
        ring.push(i, payload=None)
    with pytest.raises(RingBackpressure) as exc:
        ring.push(9, payload=None)
    assert exc.value.reason == "full"
    assert not ring.wedged               # full is transient, not fatal
    # completing frees a slot again
    d = ring.pop()
    ring.complete(d.slot, d.seq)
    ring.push(9, payload=None)


def test_ring_wedged_rejects_typed():
    ring = DescriptorRing(4)
    ring.wedge()
    with pytest.raises(RingBackpressure) as exc:
        ring.push(0, payload=None)
    assert exc.value.reason == "wedged"
    ring.reset()
    ring.push(0, payload=None)           # recovery path


def test_ring_out_of_order_stamp_wedges():
    ring = DescriptorRing(4)
    ring.push(0, payload=None)
    ring.push(1, payload=None)
    d1 = ring.pop()
    d2 = ring.pop()
    with pytest.raises(RingBackpressure) as exc:
        ring.complete(d2.slot, d2.seq)   # seq 2 before seq 1: protocol
    assert exc.value.reason == "wedged"
    assert ring.wedged                   # corruption, not scheduling
    with pytest.raises(RingBackpressure):
        ring.push(2, payload=None)
    # d1 exists only to show the FIFO expectation; the wedge is sticky
    assert d1.seq == 1 and ring.completed == 0


def test_ring_drain_barrier_completes_and_times_out():
    ring = DescriptorRing(4)
    ring.drain_barrier()                 # nothing pushed: immediate
    ring.push(0, payload=None)
    d = ring.pop()
    ring.complete(d.slot, d.seq)
    ring.drain_barrier()                 # everything stamped: immediate
    ring.push(1, payload=None)           # in flight, never stamped
    with pytest.raises(RingBackpressure) as exc:
        ring.drain_barrier(timeout_s=0.01)
    assert exc.value.reason == "wedged" and ring.wedged


def test_ring_capacity_must_be_pow2():
    with pytest.raises(ValueError):
        DescriptorRing(6)
    with pytest.raises(ValueError):
        DescriptorRing(1)


# --------------------------------------------------- resident serving

PROFILE = "q=4,;rows=16,;keys=4,;ops=or,and;heads=both;pool=16,;expr=2;"


def _mk_tenant(seed: int, uni: int, vmax: int):
    rng = np.random.default_rng(seed)
    bms = [RoaringBitmap.from_values(np.unique(
        rng.integers(0, uni, 500)).astype(np.uint32)) for _ in range(4)]
    ds = DeviceBitmapSet(bms, layout="dense")
    ids = np.unique(rng.integers(0, uni, 1200)).astype(np.uint32)
    col = BsiColumn("price", ids,
                    rng.integers(0, vmax, ids.size).astype(np.int64))
    ds.attach_column(col)
    return bms, ds, col


@pytest.fixture(scope="module")
def tenants():
    return [_mk_tenant(0x161, 1 << 12, 400),
            _mk_tenant(0x162, 1 << 11, 120)]


@pytest.fixture(scope="module")
def warmed(tenants):
    """ONE warmed engine + sealed lattice for the whole module — the
    vocabulary compile is the expensive part, and the compiled programs
    live in the engine's LRUs, so tests re-activate the SAME lattice
    (``from_profile`` passes a Lattice through) instead of re-warming.
    The autouse ``_clean`` deactivates between tests; each test that
    needs the warm state starts with ``rt_lattice.activate(lat)``."""
    depth = max(c.depth_pad for _, _, c in tenants)
    eng = MultiSetBatchEngine([ds for _, ds, _ in tenants])
    eng.warmup(profile=PROFILE + f"bsi={depth},")
    lat = rt_lattice.active()
    assert lat is not None and lat.sealed
    yield eng, lat
    rt_lattice.deactivate()


def _queries(i: int):
    if i % 2:
        return expr.ExprQuery(expr.sum_(
            "price", found=expr.and_(expr.or_(0, 1),
                                     expr.cmp("price", "ge", 5 + i))))
    return expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                    expr.cmp("price", "le", 60 + i)))


def _check_ticket(t, tenants):
    assert t.status == "done", (t.status, t.error)
    bms, _, col = tenants[t.request.set_id]
    q = t.request.query
    if expr.is_agg(q.expr):
        card, value, _ = expr.evaluate_host_agg(q.expr, bms,
                                                {"price": col})
        assert (t.result.cardinality, t.result.value) == (card, value)
    else:
        ref = expr.evaluate_host(q.expr, bms, {"price": col})
        assert t.result.cardinality == ref.cardinality


def test_signature_id_closed_enum(warmed):
    _eng, lat = warmed
    # dispatch shapes: the flat cross product (expr/bsi/delta
    # MARKER points are shape-classes, not pool shapes — their
    # default q=1 is outside the rungs and they get no id)
    flat = [p for p in lat.enumerate_points(pooled=True)
            if p.q in lat.q and not p.delta]
    assert flat
    seen = {}
    for point in flat:
        sig = signature_id(lat, point)
        assert sig is not None and sig >= 0, point
        assert sig not in seen, (point, seen[sig])  # injective
        seen[sig] = point
    for point in lat.enumerate_points(pooled=True):
        if point.q not in lat.q or point.delta:
            assert signature_id(lat, point) is None, point


def test_resident_serves_64_pools_zero_dispatch(tenants, warmed):
    """The acceptance pin: >= 64 pools ring-served end-to-end with the
    per-pool host dispatch counter FLAT, bit-exact vs the host BSI
    oracle."""
    eng, lat = warmed
    rt_lattice.activate(lat)
    loop = ServingLoop(eng, ServingPolicy(
        resident=True, pool_target=2, engine="megakernel",
        default_deadline_ms=600_000.0, guard=NOSLEEP))
    arrivals = [(i * 1e-4, ServingRequest(i % 2, _queries(i),
                                          tenant=f"t{i % 2}"))
                for i in range(128)]
    d0 = obs_metrics.counter("rb_serving_dispatches_total",
                             site="serving").value
    tickets = replay_stream(loop, arrivals)
    d1 = obs_metrics.counter("rb_serving_dispatches_total",
                             site="serving").value
    assert d1 == d0, "a ring-served pool paid a host dispatch"
    assert loop._resident.stats["served"] >= 64
    assert loop._resident.stats["demoted"] == 0
    for t in tickets:
        _check_ticket(t, tenants)


def test_wedged_ring_demotes_typed_and_bit_exact(tenants, warmed):
    eng, lat = warmed
    rt_lattice.activate(lat)
    loop = ServingLoop(eng, ServingPolicy(
        resident=True, pool_target=2, engine="megakernel",
        default_deadline_ms=600_000.0, guard=NOSLEEP))
    loop._resident.ring.wedge()
    dem0 = obs_metrics.counter("rb_serving_resident_demotions_total",
                               site="serving",
                               reason="wedged").value
    d0 = obs_metrics.counter("rb_serving_dispatches_total",
                             site="serving").value
    tickets = [loop.submit(ServingRequest(0, _queries(i),
                                          tenant="t0"))
               for i in range(2)]
    loop.drain()
    assert obs_metrics.counter("rb_serving_resident_demotions_total",
                               site="serving",
                               reason="wedged").value == dem0 + 1
    assert obs_metrics.counter("rb_serving_dispatches_total",
                               site="serving").value > d0
    for t in tickets:
        _check_ticket(t, tenants)


def test_inactive_vocab_escape(tenants):
    # NO warmup: no sealed lattice, so the queue must refuse activation
    # and serve() must escape typed
    eng = MultiSetBatchEngine([ds for _, ds, _ in tenants])
    rq = ResidentQueue(eng)
    assert not rq.seal_vocab() and not rq.active
    with pytest.raises(ResidentEscape) as exc:
        rq.serve([BatchGroup(0, [_queries(0)])])
    assert exc.value.reason == "inactive"


def test_backend_escape_is_typed(warmed):
    _eng, lat = warmed
    rt_lattice.activate(lat)

    class NotAnEngine:
        pass

    rq = ResidentQueue(NotAnEngine())
    assert rq.seal_vocab()           # the lattice governs...
    with pytest.raises(ResidentEscape) as exc:
        rq.serve([BatchGroup(0, [_queries(0)])])
    assert exc.value.reason == "backend"  # ...the backend cannot


def test_vocabulary_escape_flat_only_pool(warmed):
    # a pool with NO fused section assembles no one-kernel program —
    # the resident lane refuses it even though the lattice covers the
    # shape (the megakernel is the expression assembler)
    eng, lat = warmed
    rt_lattice.activate(lat)
    rq = ResidentQueue(eng)
    assert rq.seal_vocab()
    with pytest.raises(ResidentEscape) as exc:
        rq.serve([BatchGroup(0, [BatchQuery("or", (0, 1, 2))])])
    assert exc.value.reason == "vocabulary"


def test_vocabulary_escape_unwarmed_shape(warmed):
    # a fused pool whose snapped point is OUTSIDE the sealed vocabulary
    # (expression depth 3 vs the warmed expr=2 rung) cannot even be
    # described to the consumer
    eng, lat = warmed
    rt_lattice.activate(lat)
    rq = ResidentQueue(eng)
    assert rq.seal_vocab()
    deep = expr.ExprQuery(expr.and_(
        expr.or_(expr.and_(0, 1), expr.and_(1, 2)),
        expr.cmp("price", "le", 50)))
    with pytest.raises(ResidentEscape) as exc:
        rq.serve([BatchGroup(0, [deep])])
    assert exc.value.reason == "vocabulary"


def test_wedged_push_escape_counts_demotion(warmed):
    eng, lat = warmed
    rt_lattice.activate(lat)
    rq = ResidentQueue(eng)
    assert rq.seal_vocab()
    rq.ring.wedge()
    with pytest.raises(ResidentEscape) as exc:
        rq.serve([BatchGroup(0, [_queries(0), _queries(2)])])
    assert exc.value.reason == "wedged"
    assert rq.stats["demoted"] == 1 and rq.stats["served"] == 0


def test_resident_queue_env_opt_in(tenants, monkeypatch):
    monkeypatch.setenv("ROARING_TPU_SERVING_RESIDENT", "1")
    assert ServingPolicy.from_env().resident
    monkeypatch.setenv("ROARING_TPU_SERVING_RESIDENT", "0")
    assert not ServingPolicy.from_env().resident
