"""Tests for the API-surface components: writer wizard + appenders,
insights, FastRankRoaringBitmap, RoaringBitSet/BitSetUtil, iterator
flyweights (SURVEY §2.1 rows: Builders, insights, FastRank, RoaringBitSet,
BitSetUtil, Iterators)."""

import numpy as np
import pytest

from roaringbitmap_tpu import (
    FastRankRoaringBitmap,
    RoaringBitmap,
    RoaringBitmapWriter,
    RoaringBitSet,
)
from roaringbitmap_tpu.core import bitset as bsu
from roaringbitmap_tpu.core.iterators import (
    PeekableIntIterator,
    PeekableIntRankIterator,
    ReverseIntIterator,
)
from roaringbitmap_tpu.insights import (
    BitmapAnalyser,
    NaiveWriterRecommender,
    analyse,
)


class TestWriter:
    def test_wizard_fluent(self):
        w = (RoaringBitmapWriter.wizard().optimise_for_runs()
             .expected_range(0, 1 << 20).initial_capacity(8)
             .expected_container_size(32).get())
        assert isinstance(w, RoaringBitmapWriter)
        assert w.optimize_for_runs

    def test_out_of_order_adds(self, rng):
        vals = rng.permutation(rng.integers(0, 1 << 22, 20000,
                                            dtype=np.uint32))
        w = RoaringBitmapWriter.wizard().get()
        for v in vals[:100]:
            w.add(int(v))
        w.add_many(vals[100:])
        got = w.get()
        assert got == RoaringBitmap.from_values(vals)

    def test_constant_memory_sequential(self):
        w = RoaringBitmapWriter.wizard().constant_memory().get()
        vals = np.arange(0, 200000, 3, dtype=np.uint32)
        w.add_many(vals)
        assert w.get() == RoaringBitmap.from_values(vals)

    def test_constant_memory_key_revisit(self):
        """Revisiting an earlier chunk after a flush still lands (ior)."""
        w = RoaringBitmapWriter.wizard().constant_memory().get()
        for v in (5, 70000, 6):
            w.add(v)
        assert sorted(w.get()) == [5, 6, 70000]

    def test_run_optimized_output(self):
        w = RoaringBitmapWriter.wizard().optimise_for_runs().get()
        w.add_range(1000, 200000)
        out = w.get()
        assert out.has_run_compression()
        assert out.cardinality == 199000

    def test_default_writer_run_compresses(self):
        """runCompress defaults on: consecutive values come out run-encoded
        for the buffered writer, matching the constant-memory path."""
        w = RoaringBitmapWriter.wizard().get()
        w.add_many(np.arange(8000, dtype=np.uint32))
        out = w.get()
        assert out.has_run_compression()
        assert out.serialized_size_in_bytes() < 100
        w2 = RoaringBitmapWriter.wizard().run_compress(False).get()
        w2.add_many(np.arange(8000, dtype=np.uint32))
        assert not w2.get().has_run_compression()

    def test_reset(self):
        w = RoaringBitmapWriter.wizard().get()
        w.add(1)
        w.reset()
        w.add(2)
        assert sorted(w.get()) == [2]


class TestInsights:
    def test_analyse_counts(self, rng):
        rb = RoaringBitmap.from_values(
            rng.integers(0, 1 << 22, 200000, dtype=np.uint32))  # dense-ish
        rb.ior(RoaringBitmap.from_values(
            np.array([1 << 28, (1 << 28) + 2], dtype=np.uint32)))  # array
        rb.add_range(1 << 30, (1 << 30) + 100000)
        rb.run_optimize()
        stats = analyse(rb)
        assert stats.container_count() == rb.container_count()
        assert stats.run_containers_count >= 1
        assert stats.array_stats.containers_count >= 1
        assert stats.bitmaps_count == 1
        frac = stats.container_fraction(stats.run_containers_count)
        assert 0 <= frac <= 1

    def test_analyse_all_merge(self, rng):
        bms = [RoaringBitmap.from_values(
            rng.integers(0, 1 << 20, 5000, dtype=np.uint32)) for _ in range(4)]
        stats = BitmapAnalyser.analyse_all(bms)
        assert stats.bitmaps_count == 4
        assert stats.container_count() == sum(b.container_count() for b in bms)

    def test_recommender(self):
        rb = RoaringBitmap.from_range(0, 1 << 20)
        rb.run_optimize()
        advice = NaiveWriterRecommender.recommend_for(rb)
        assert any("optimise_for_runs" in a for a in advice)
        empty_advice = NaiveWriterRecommender.recommend(analyse(RoaringBitmap()))
        assert empty_advice


class TestFastRank:
    def test_rank_select_match_base(self, rng):
        vals = np.unique(rng.integers(0, 1 << 24, 30000, dtype=np.uint32))
        fr = FastRankRoaringBitmap.from_values(vals)
        base = RoaringBitmap.from_values(vals)
        for j in range(0, vals.size, 3001):
            assert fr.select(j) == base.select(j) == int(vals[j])
            assert fr.rank(int(vals[j])) == base.rank(int(vals[j]))
        assert fr.cache_valid

    def test_mutation_invalidates(self):
        fr = FastRankRoaringBitmap.from_values(
            np.array([1, 5, 100000], dtype=np.uint32))
        assert fr.select(2) == 100000
        assert fr.cache_valid
        fr.add(50)
        assert not fr.cache_valid
        assert fr.select(1) == 5 and fr.select(2) == 50
        assert fr.rank(100000) == 4

    def test_clear_invalidates(self):
        fr = FastRankRoaringBitmap.from_values(
            np.array([1, 2, 3], dtype=np.uint32))
        assert fr.select(0) == 1
        fr.clear()
        with pytest.raises(ValueError):
            fr.select(0)

    def test_is_roaring_bitmap(self):
        fr = FastRankRoaringBitmap.from_values(np.array([3], dtype=np.uint32))
        assert isinstance(fr, RoaringBitmap)
        assert fr == RoaringBitmap.bitmap_of(3)


class TestBitSetUtil:
    def test_words_roundtrip(self, rng):
        words = rng.integers(0, 2 ** 63, 2500, dtype=np.uint64)
        rb = bsu.bitmap_of_words(words)
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        assert rb.cardinality == int(bits.sum())
        back = bsu.bitset_of(rb, words.size)
        assert np.array_equal(back, words)

    def test_bool_array_roundtrip(self, rng):
        mask = rng.random(100000) < 0.3
        rb = bsu.bitmap_of_bool_array(mask)
        assert np.array_equal(bsu.bool_array_of(rb, mask.size), mask)

    def test_bitset_of_too_small_raises(self):
        rb = RoaringBitmap.bitmap_of(1000)
        with pytest.raises(ValueError):
            bsu.bitset_of(rb, 1)


class TestRoaringBitSet:
    def test_set_get_clear_flip(self):
        bs = RoaringBitSet()
        bs.set(5)
        bs.set(100, 200)
        assert bs.get(5) and bs.get(150) and not bs.get(99)
        assert bs.cardinality() == 101
        bs.clear(100, 150)
        assert bs.cardinality() == 51
        bs.flip(5)
        assert not bs.get(5)
        bs.set(7, value=False)
        assert not bs.get(7)

    def test_java_style_set_value_overload(self):
        bs = RoaringBitSet()
        bs.set(7, True)  # BitSet.set(int, boolean)
        assert bs.get(7)
        bs.set(7, False)
        assert not bs.get(7)

    def test_logical_ops(self):
        a = RoaringBitSet(RoaringBitmap.bitmap_of(1, 2, 3, 70000))
        b = RoaringBitSet(RoaringBitmap.bitmap_of(2, 3, 4))
        a.and_(b)
        assert sorted(a.stream()) == [2, 3]
        a2 = RoaringBitSet(RoaringBitmap.bitmap_of(1, 2))
        a2.or_(b)
        assert sorted(a2.stream()) == [1, 2, 3, 4]
        a3 = RoaringBitSet(RoaringBitmap.bitmap_of(1, 2))
        a3.xor(b)
        assert sorted(a3.stream()) == [1, 3, 4]
        a4 = RoaringBitSet(RoaringBitmap.bitmap_of(1, 2))
        a4.and_not(b)
        assert sorted(a4.stream()) == [1]

    def test_navigation_and_length(self):
        bs = RoaringBitSet(RoaringBitmap.bitmap_of(3, 10, 500000))
        assert bs.next_set_bit(4) == 10
        assert bs.next_clear_bit(3) == 4
        assert bs.previous_set_bit(9) == 3
        assert bs.length() == 500001
        assert bs.size() % 64 == 0 and bs.size() >= bs.length()
        assert bs.value_of(bs.to_word_array()) == bs


class TestIterators:
    def test_peekable(self):
        rb = RoaringBitmap.bitmap_of(1, 5, 9, 70000)
        it = PeekableIntIterator(rb)
        assert it.peek_next() == 1
        it.advance_if_needed(6)
        assert it.peek_next() == 9
        assert list(it) == [9, 70000]

    def test_advance_not_backward(self):
        rb = RoaringBitmap.bitmap_of(10, 20)
        it = PeekableIntIterator(rb)
        it.next()
        it.advance_if_needed(5)  # no-op: already past
        assert it.peek_next() == 20

    def test_rank_iterator(self):
        rb = RoaringBitmap.bitmap_of(4, 8, 15)
        it = PeekableIntRankIterator(rb)
        assert it.peek_next_rank() == 1
        it.next()
        assert it.peek_next_rank() == 2

    def test_reverse(self):
        rb = RoaringBitmap.bitmap_of(1, 5, 70000)
        assert list(ReverseIntIterator(rb)) == [70000, 5, 1]

    def test_clone_independent(self):
        rb = RoaringBitmap.bitmap_of(1, 2, 3)
        it = PeekableIntIterator(rb)
        it.next()
        c = it.clone()
        it.next()
        assert c.peek_next() == 2 and it.peek_next() == 3


class TestIteratorFlyweight:
    """The flyweight guarantee (IntIteratorFlyweight.java): walking never
    materializes more than the current container's values."""

    def _rb(self):
        vals = np.concatenate([
            np.arange(0, 8000, 2, dtype=np.uint32),         # array chunk
            np.arange(1 << 16, (1 << 16) + 70000),          # bitmap+run chunks
            np.array([5 << 16, (5 << 16) + 9], dtype=np.uint32)])
        return RoaringBitmap.from_values(vals.astype(np.uint32))

    def test_full_walk_parity(self):
        rb = self._rb()
        assert np.array_equal(np.fromiter(PeekableIntIterator(rb), np.uint32),
                              rb.to_array())
        assert np.array_equal(
            np.fromiter(ReverseIntIterator(rb), np.uint32),
            rb.to_array()[::-1])

    def test_memory_is_one_container(self):
        rb = self._rb()
        it = PeekableIntIterator(rb)
        # current buffer is bounded by one container, not the cardinality
        assert it._cur.size <= 1 << 16 < rb.cardinality

    def test_advance_skips_containers_without_expanding(self):
        rb = self._rb()
        it = PeekableIntIterator(rb)
        it.advance_if_needed((5 << 16) + 1)
        assert it.peek_next() == (5 << 16) + 9
        # advance into a gap key: lands on next present container
        it2 = PeekableIntIterator(rb)
        it2.advance_if_needed(4 << 16)
        assert it2.peek_next() == 5 << 16

    def test_rank_across_containers(self):
        rb = self._rb()
        it = PeekableIntRankIterator(rb)
        it.advance_if_needed(1 << 16)  # first value of the second chunk
        assert it.peek_next() == 1 << 16
        assert it.peek_next_rank() == 4001  # 4000 values in chunk 0
        it.advance_if_needed(5 << 16)
        assert it.peek_next_rank() == 4001 + 70000

    def test_advance_past_everything(self):
        it = PeekableIntIterator(self._rb())
        it.advance_if_needed(0xFFFFFFFF)
        assert not it.has_next()

    def test_empty_bitmap(self):
        it = PeekableIntIterator(RoaringBitmap())
        assert not it.has_next()
        assert not ReverseIntIterator(RoaringBitmap()).has_next()

    def test_structural_mutation_does_not_desync(self):
        # snapshot semantics: adding to the bitmap after iterator creation
        # must not crash or corrupt an in-flight walk (regression: aliased
        # keys/containers desynced when _insert rebound them)
        rb = RoaringBitmap.bitmap_of(1 << 16, (1 << 16) + 5)
        it = PeekableIntIterator(rb)
        rb.add(3)   # structural insert BEFORE the iterated key
        assert list(it) == [1 << 16, (1 << 16) + 5]
        rit = ReverseIntIterator(rb)
        rb.add(9 << 16)
        assert list(rit) == [(1 << 16) + 5, 1 << 16, 3]


class TestReferenceParityMethods:
    """The long tail of RoaringBitmap.java public surface."""

    def _rb(self):
        return RoaringBitmap.from_values(np.array(
            [3, 7, 100, 65536, 0x80000000, 0xFFFFFFFF], dtype=np.uint32))

    def test_for_each_family(self):
        rb = self._rb()
        seen = []
        rb.for_each(seen.append)
        assert seen == rb.to_array().tolist()
        seen2 = []
        rb.for_each_in_range(5, 70000, seen2.append)
        assert seen2 == [7, 100, 65536]
        bits = []
        rb.for_all_in_range(6, 9, lambda rel, present: bits.append((rel, present)))
        assert bits == [(0, False), (1, True), (2, False)]

    def test_iterator_getters(self):
        rb = self._rb()
        assert list(rb.get_int_iterator()) == rb.to_array().tolist()
        assert list(rb.get_reverse_int_iterator()) == rb.to_array()[::-1].tolist()
        signed = list(rb.get_signed_int_iterator())
        assert signed == [-(1 << 31), -1, 3, 7, 100, 65536]

    def test_signed_bounds(self):
        rb = self._rb()
        assert rb.first_signed() == -(1 << 31)
        assert rb.last_signed() == 65536
        pos_only = RoaringBitmap.bitmap_of(5, 9)
        assert pos_only.first_signed() == 5 and pos_only.last_signed() == 9
        neg_only = RoaringBitmap.bitmap_of(0xFFFFFFF0)
        assert neg_only.first_signed() == -16 and neg_only.last_signed() == -16

    def test_cardinality_exceeds_and_select_range(self):
        rb = self._rb()
        assert rb.cardinality_exceeds(5) and not rb.cardinality_exceeds(6)
        sel = rb.select_range(1, 4)
        assert sel.to_array().tolist() == [7, 100, 65536]
        with pytest.raises(ValueError):
            rb.select_range(10, 12)

    def test_aliases(self):
        rb = self._rb()
        assert rb.rank_long(100) == rb.rank(100) == 3
        assert rb.long_cardinality == rb.cardinality
        assert rb.get_long_size_in_bytes() == rb.get_size_in_bytes()
        rb.trim()  # no-op, must exist
        assert RoaringBitmap.bitmap_of_unordered([9, 1, 5]) == \
            RoaringBitmap.bitmap_of(1, 5, 9)
        assert RoaringBitmap.maximum_serialized_size(100, 1 << 20) > 200

    def test_signed_bounds_empty_raises(self):
        with pytest.raises(ValueError, match="empty"):
            RoaringBitmap().first_signed()
        with pytest.raises(ValueError, match="empty"):
            RoaringBitmap().last_signed()


class TestImmutableLongTail:
    def _im(self):
        from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap
        rb = RoaringBitmap.from_values(np.array(
            [3, 7, 100, 65536, 0x80000000], dtype=np.uint32))
        return ImmutableRoaringBitmap(rb.serialize()), rb

    def test_delegated_surface(self):
        im, rb = self._im()
        seen = []
        im.for_each(seen.append)
        assert seen == rb.to_array().tolist()
        assert list(im.get_int_iterator()) == rb.to_array().tolist()
        assert im.first_signed() == rb.first_signed()
        assert im.last_signed() == rb.last_signed()
        assert im.range_cardinality(5, 70000) == rb.range_cardinality(5, 70000)
        assert im.rank_long(100) == rb.rank(100)
        assert im.long_cardinality == rb.cardinality
        assert im.select_range(1, 3) == rb.select_range(1, 3)
        assert im.next_value(8) == rb.next_value(8)
        assert im.previous_absent_value(100) == rb.previous_absent_value(100)
        assert im.limit(2) == rb.limit(2)

    def test_cardinality_exceeds_header_only(self):
        im, rb = self._im()
        assert im.cardinality_exceeds(4) and not im.cardinality_exceeds(5)
        assert not im._cache  # header-only: nothing decoded

    def test_lazy_navigation_touches_minimal_containers(self):
        from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap
        rb = RoaringBitmap.from_values(np.concatenate([
            np.arange(0, 100, dtype=np.uint32),
            (1 << 16) + np.arange(0, 100, dtype=np.uint32),
            (5 << 16) + np.arange(0, 100, dtype=np.uint32)]))
        im = ImmutableRoaringBitmap(rb.serialize())
        assert im.next_value(50) == rb.next_value(50) == 50
        assert im.previous_value((1 << 16) + 5000) == \
            rb.previous_value((1 << 16) + 5000)
        assert im.next_value((6 << 16)) == rb.next_value((6 << 16)) == -1
        assert im.previous_value(0) == rb.previous_value(0) == 0
        # only query-touched containers decode (lazy sequence, no full list)
        assert len(im._cache) <= 3
        sel = im.select_range(150, 250)
        assert sel == rb.select_range(150, 250)
        assert im.limit(5) == rb.limit(5)


class TestWriterRandomised:
    """RoaringBitmapWriterRandomisedTest: the writer must build the same
    bitmap as bulk construction for random unordered inputs across four
    orders of magnitude, via point adds, add_many, and both appender
    strategies (shouldBuildSameBitmapAsBitmapOf*)."""

    @pytest.mark.parametrize("n", [4, 0, 10, 100, 1000, 10_000, 100_000])
    def test_point_adds_match_bulk(self, rng, n):
        values = (np.arange(4, dtype=np.uint32) if n == 4
                  else rng.integers(0, 1 << 26, n).astype(np.uint32))
        want = RoaringBitmap.from_values(values)
        w = RoaringBitmapWriter.wizard().get()
        for v in values.tolist():
            w.add(int(v))
        w.flush()
        assert w.get_underlying() == want

    @pytest.mark.parametrize("n", [1000, 100_000])
    @pytest.mark.parametrize("constant_memory", [False, True])
    def test_add_many_matches_bulk(self, rng, n, constant_memory):
        values = rng.integers(0, 1 << 28, n).astype(np.uint32)
        want = RoaringBitmap.from_values(values)
        wiz = RoaringBitmapWriter.wizard()
        if constant_memory:
            wiz = wiz.constant_memory()
        w = wiz.get()
        w.add_many(values)
        w.flush()
        assert w.get_underlying() == want


class TestRoaringBitSetModel:
    """RoaringBitSetTest.testLogicalIdentities analog: randomized BitSet
    surface vs a Python-set oracle (the reference models against
    java.util.BitSet)."""

    def test_randomized_vs_set_oracle(self, rng):
        bs = RoaringBitSet()
        ref: set[int] = set()
        universe = 1 << 18
        for _ in range(400):
            op = int(rng.integers(5))
            i = int(rng.integers(universe))
            j = i + int(rng.integers(1, 5000))
            if op == 0:
                bs.set(i)
                ref.add(i)
            elif op == 1:
                bs.set(i, j)
                ref.update(range(i, j))
            elif op == 2:
                bs.clear(i, j)
                ref.difference_update(range(i, j))
            elif op == 3:
                bs.flip(i, j)
                ref.symmetric_difference_update(range(i, j))
            else:
                assert bs.get(i) == (i in ref)
        assert sorted(bs.stream().tolist()) == sorted(ref)
        assert bs.cardinality() == len(ref)
        if ref:
            assert bs.length() == max(ref) + 1
            probe = min(ref)
            assert bs.next_set_bit(probe) == probe
        # logical identities vs a second random set
        other_vals = rng.integers(0, universe, 4000).astype(np.uint32)
        other = RoaringBitSet(RoaringBitmap.from_values(other_vals))
        oref = set(other_vals.tolist())
        for name, fold in (("and_", ref & oref), ("or_", ref | oref),
                           ("xor", ref ^ oref), ("and_not", ref - oref)):
            c = RoaringBitSet(bs.to_bitmap().clone())
            getattr(c, name)(other)
            assert sorted(c.stream().tolist()) == sorted(fold), name


class TestExpertSurface:
    """The last unmapped names from the reference sweep: append (expert
    container splice, RoaringBitmap.java:3237), getContainerPointer
    (ContainerPointer.java:16-61), bitmapOfRange, toMutableRoaringBitmap,
    and the camelCase-familiar andNot aliases."""

    def test_append_and_pointer(self):
        rb = RoaringBitmap.bitmap_of(1, 2, 3)
        from roaringbitmap_tpu.core import containers as C

        rb.append(5, C.ArrayContainer(np.array([7, 9], dtype=np.uint16)))
        assert rb.contains((5 << 16) + 7) and rb.cardinality == 5
        with pytest.raises(ValueError, match="not above"):
            rb.append(5, C.ArrayContainer(np.array([1], dtype=np.uint16)))
        with pytest.raises(ValueError, match="key space"):
            rb.append(1 << 16, C.ArrayContainer(np.array([1], np.uint16)))
        with pytest.raises(ValueError, match="empty container"):
            rb.append(9, C.ArrayContainer(np.empty(0, np.uint16)))
        ptr = rb.get_container_pointer()
        seen = []
        while ptr.has_container():
            seen.append((ptr.key(), ptr.get_cardinality(),
                         ptr.is_run_container(), ptr.is_bitmap_container()))
            ptr.advance()
        assert seen == [(0, 3, False, False), (5, 2, False, False)]
        assert ptr.get_container() is None
        p2 = rb.get_container_pointer()
        p3 = p2.clone()
        p2.advance()
        assert p3.key() == 0 and p2.key() == 5  # clones are independent

    def test_range_builder_and_mutable_conversion(self):
        import roaringbitmap_tpu as rt
        from roaringbitmap_tpu.buffer import MutableRoaringBitmap

        rb = RoaringBitmap.bitmap_of_range(10, 200000)
        assert rb == RoaringBitmap.from_range(10, 200000)
        mut = rb.to_mutable_roaring_bitmap()
        assert isinstance(mut, MutableRoaringBitmap) and mut == rb
        mut.add(5)  # copies: the source must not see the mutation
        assert not rb.contains(5)
        a, b = RoaringBitmap.bitmap_of(1, 2), RoaringBitmap.bitmap_of(2)
        assert rt.and_not(a, b) == rt.andnot(a, b)
        assert rt.and_not_cardinality(a, b) == 1


def test_wizard_fast_rank_knob(rng):
    """fastRank() wizard knob (TestRoaringBitmapWriterWizard:17-26): the
    built bitmap is a FastRankRoaringBitmap, on both appender strategies."""
    from roaringbitmap_tpu.core.fastrank import FastRankRoaringBitmap

    vals = rng.integers(0, 1 << 20, 5000).astype(np.uint32)
    for wiz in (RoaringBitmapWriter.wizard().fast_rank(),
                RoaringBitmapWriter.wizard().fast_rank().constant_memory()):
        w = wiz.get()
        w.add_many(vals)
        out = w.get()
        assert isinstance(out, FastRankRoaringBitmap)
        assert out == RoaringBitmap.from_values(vals)
        mid = out.select(out.cardinality // 2)  # rank cache path works
        assert out.rank(mid) == out.cardinality // 2 + 1


def test_empty_bitmap_iterators():
    """TestEmptyRoaringBatchIterator + empty flyweight edges: every
    iterator form over an empty bitmap terminates immediately, including
    after seeks, on both tiers."""
    from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap

    for rb in (RoaringBitmap(), ImmutableRoaringBitmap(
            RoaringBitmap().serialize())):
        bi = rb.get_batch_iterator(16)
        assert not bi.has_next() and bi.next_batch().size == 0
        bi.advance_if_needed(12345)
        assert not bi.has_next()
        assert bi.clone().next_batch().size == 0
        assert list(rb.get_int_iterator()) == []
        assert list(rb.get_reverse_int_iterator()) == []
        it = rb.get_int_iterator()
        it.advance_if_needed(7)
        assert not it.has_next()
        with pytest.raises(StopIteration):
            it.peek_next()
        assert list(rb.batch_iterator(8)) == []
