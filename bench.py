"""Benchmark: wide-OR aggregation throughput on census1881 (driver metric).

Measures the north-star workload from BASELINE.json: FastAggregation/
ParallelAggregation-style wide OR over the census1881 real-roaring-dataset
(200 bitmaps), executed on device from HBM-resident packed containers, with
exact cardinality asserted every run.

Methodology
- CPU baseline: baselines/cpu_baseline.json — the C++ -O3 translation of the
  JVM ParallelAggregation.or algorithm (no JVM exists in this image; see
  baselines/wide_or_cpu.cpp).  Falls back to this host's Python fold only if
  the file is missing, and labels the result accordingly.
- Device steady state: the TPU here sits behind a network tunnel, so a
  single dispatch costs ~90 ms RTT.  We therefore run two chained-rep
  programs (R1 and R2 dependent wide-ORs inside one jit) and report the
  *marginal* cost (t2 - t1) / (R2 - R1): pure on-device per-op time with
  dispatch/sync amortized out — the same quantity the CPU ns/op measures.
  Every chained program's summed cardinality is asserted == reps * expected,
  proving each iteration really ran bit-exact.
- Cold path: pack (host rotation+densify) + transfer + first dispatch are
  timed and reported separately; steady state assumes HBM residency (the
  ImmutableRoaringBitmap stays-mmap'd usage, README.md:198-274).

--profile writes a jax.profiler trace (the JMH -prof analog) to
  /tmp/rb_tpu_trace and reports per-engine device ms from it.

Prints ONE JSON line with metric/value/unit/vs_baseline + detail.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import time

import numpy as np


R1, R2 = 100, 1100  # chained rep counts; marginal = (t2-t1)/(R2-R1)


def load_cpu_baseline() -> tuple[float | None, dict]:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baselines", "cpu_baseline.json")
    if not os.path.exists(path):
        return None, {}
    with open(path) as f:
        data = json.load(f)
    row = data.get("datasets", {}).get("census1881", {}).get("wide_or")
    if not row:
        return None, {}
    return row["ns_per_op_avg"] / 1e9, {
        "source": "baselines/cpu_baseline.json (C++ -O3, "
                  "ParallelAggregation.or algorithm, single thread)",
        "cpu_result_cardinality": row["result_cardinality"],
        "reps": row["reps"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", action="store_true",
                    help="capture a jax.profiler trace of the measured runs")
    args = ap.parse_args()

    import jax

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.utils import datasets

    if datasets.has_dataset("census1881"):
        arrs = datasets.load_value_arrays("census1881")
        dataset = "census1881"
    else:
        dataset = "synthetic"
        rng = np.random.default_rng(0)
        arrs = [rng.integers(0, 1 << 24, 50000).astype(np.uint32)
                for _ in range(200)]

    bitmaps = [RoaringBitmap.from_values(a) for a in arrs]
    oracle_card = int(np.unique(np.concatenate(arrs)).size)
    backend = jax.default_backend()

    # ---- CPU baseline (census-specific; never applied to the synthetic
    # fallback workload)
    cpu_s, cpu_info = (load_cpu_baseline() if dataset == "census1881"
                       else (None, {}))
    if cpu_s is None:
        t0 = time.perf_counter()
        acc = bitmaps[0].clone()
        for b in bitmaps[1:]:
            acc.ior(b)
        cpu_s = time.perf_counter() - t0
        assert acc.cardinality == oracle_card, "host fold parity failure"
        cpu_info = {"source": "python host fold (no cpu_baseline.json — "
                              "NOT an optimized baseline)"}
    else:
        assert cpu_info.pop("cpu_result_cardinality") == oracle_card, \
            "C++ baseline cardinality drift"

    # ---- cold path: pack + transfer + first aggregation, end to end
    t0 = time.perf_counter()
    ds = DeviceBitmapSet(bitmaps)
    t_pack = time.perf_counter() - t0
    words0, cards0 = ds.aggregate_device("or", engine="xla")
    total0 = int(np.asarray(cards0.sum()))
    t_cold = time.perf_counter() - t0
    assert total0 == oracle_card, "device parity failure (single shot)"

    # ---- steady state per engine: marginal chained cost
    r1, r2 = R1, R2

    def chained_seconds(engine: str, reps: int) -> float:
        """Best-of-3 timed runs of one compiled chained program (the RTT to
        the tunneled TPU adds ~10 ms of per-dispatch noise; min is the
        noise-robust estimator)."""
        expected = (reps * oracle_card) % 2**32  # uint32 accumulator
        fn = ds.chained_wide_or(reps, engine=engine)
        best = float("inf")
        for i in range(4):  # first call compiles + warms up, then 3 timed
            t0 = time.perf_counter()
            total = int(np.asarray(fn(ds.words)))
            dt = time.perf_counter() - t0
            assert total == expected, \
                f"device parity failure ({engine}): {total} != " \
                f"({reps}*{oracle_card}) mod 2^32"
            if i:
                best = min(best, dt)
        return best

    def marginal(engine: str) -> tuple[float, float]:
        """(steady-state s/op, end-to-end s/op at r2 incl. one dispatch)."""
        for _ in range(3):  # retry when scheduling noise makes t2 <= t1
            t1, t2 = chained_seconds(engine, r1), chained_seconds(engine, r2)
            if t2 > t1:
                return (t2 - t1) / (r2 - r1), t2 / r2
        raise RuntimeError(
            f"unstable timing for engine {engine}: t({r2}) <= t({r1})")

    with (jax.profiler.trace("/tmp/rb_tpu_trace") if args.profile
          else contextlib.nullcontext()):
        per_engine = {eng: marginal(eng) for eng in ("xla", "pallas")}

    engine = min(per_engine, key=lambda e: per_engine[e][0])
    dev_s, e2e_s = per_engine[engine]

    ops_per_sec = 1.0 / dev_s
    out = {
        "metric": f"wide_or_{dataset}_aggregations_per_sec",
        "value": round(ops_per_sec, 3),
        "unit": "wide-OR/s (200 bitmaps, card-exact, steady-state marginal)",
        "vs_baseline": round(cpu_s / dev_s, 3),
        "detail": {
            "backend": backend, "engine": engine,
            "marginal_us_per_wide_or": {
                k: round(v[0] * 1e6, 2) for k, v in per_engine.items()},
            "e2e_us_per_wide_or_with_dispatch": {
                k: round(v[1] * 1e6, 2) for k, v in per_engine.items()},
            "n_bitmaps": len(bitmaps), "result_cardinality": oracle_card,
            "pack_ms": round(t_pack * 1e3, 2),
            "cold_pack_transfer_first_query_ms": round(t_cold * 1e3, 2),
            "cpu_wide_or_ms": round(cpu_s * 1e3, 4),
            "cpu_baseline": cpu_info,
            "hbm_resident_mb": round(ds.hbm_bytes() / 1e6, 1),
            "chained_reps": [r1, r2],
        },
    }
    if args.profile:
        out["detail"]["profile_trace_dir"] = "/tmp/rb_tpu_trace"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
