"""Benchmark: wide-OR aggregation throughput on the driver-metric datasets.

Measures the north-star workload from BASELINE.json: FastAggregation/
ParallelAggregation-style wide OR over BOTH named real-roaring datasets
(census1881 AND wikileaks-noquotes, 200 bitmaps each), executed on device
from HBM-resident packed containers, with exact cardinality asserted every
run.  The headline metric stays census1881 (driver continuity); the
wikileaks numbers ride in detail so one artifact evidences the full target.

Methodology
- CPU baseline: baselines/cpu_baseline.json — the C++ -O3 translation of the
  JVM ParallelAggregation.or algorithm (no JVM exists in this image; see
  baselines/wide_or_cpu.cpp).  Falls back to this host's Python fold only if
  the file is missing, and labels the result accordingly.
- Device steady state: a single dispatch to the tunneled TPU carries ~ms RTT,
  so we run two chained-rep programs (R1 and R2 dependent wide-ORs inside
  one jit) and report the *marginal* cost (t2 - t1) / (R2 - R1): pure
  on-device per-op time with dispatch/sync amortized out — the same quantity
  the CPU ns/op measures.  Every chained program's summed cardinality is
  asserted == (reps * expected) mod 2^32, proving each iteration ran
  bit-exact.
- Regime note (profiler-verified): a jax.profiler trace of the chained loop
  counts exactly `reps` executions of the Pallas kernel (no elision; e.g.
  200x at 4.6 us avg device time on census1881), so the marginal is real
  per-op work.  At this working-set size (~18 MB) the chip serves repeated
  sweeps at ~3 TB/s effective — well above the ~0.74 TB/s this chip measures
  streaming a 256 MB array — i.e. the steady state is (at least partly)
  on-chip-resident; scaled to a ~99 MB resident set the same marginal drops
  to ~325 us/op (HBM-streamed).  This is symmetric with the CPU baseline:
  its 0.886 ms wide-OR is the hot-loop steady state of 50 reps over a
  2.8 MB working set sitting in L2/L3 — JMH hot-loop methodology on both
  sides, cache-resident vs cache-resident.
- Cold path: pack (host stream build + transfer + device densify) and the
  first dispatch are timed separately AFTER a device warm-up, so pack_ms is
  the steady-state ingest cost, not the one-time runtime handshake (which is
  reported as warmup_ms).  Steady state assumes HBM residency (the
  ImmutableRoaringBitmap stays-mmap'd usage, README.md:198-274).

--profile writes a jax.profiler trace (the JMH -prof analog) to
  /tmp/rb_tpu_trace and reports per-kernel device-time totals parsed from it.

Output contract (VERDICT r5 weak #1 — two rounds of `parsed: null`): the
FULL result document goes to benchmarks/bench_full.json, and stdout gets a
single COMPACT one-line JSON summary (north_star, medians + spread,
backend, batched QPS, full-doc path) as the final line.  The driver
captures a bounded tail, so the stdout line must stay small — it is hard-
capped at SUMMARY_MAX_BYTES (optional fields shed in SUMMARY_DROP_ORDER
until it fits; asserted in tests/test_bench_output.py); fd 1 is
redirected to stderr for the whole run (any library print / warning lands
there) and only the summary is written to the saved real stdout at the
end.

The two north-star cells additionally report a median + spread over
--spread fresh-process re-measurements (default 5, incl. this process) —
single-point marginals at these working-set sizes drift with VMEM
scheduling between compilations (r03 vs r04 wikileaks), so one capture
cannot distinguish variance from regression.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

R1, R2 = 100, 4100  # chained rep counts; marginal = (t2-t1)/(R2-R1)
# (gap sized so the marginal signal — ~45 ms at a 11 us/op kernel — clears
# the post-readback tunnel dispatch jitter, which measures ~10-100 ms)
BENCH_DATASETS = ("census1881", "wikileaks-noquotes")
BATCH_SIZES = (1, 8, 64, 256)   # batched multi-query lane (ISSUE 1)
BATCH_R = (10, 110)             # chained rep pair for batch marginals
MULTISET_S = (1, 4, 16)         # tenant counts of the multiset lane (ISSUE 5)
MULTISET_Q = (8, 64)            # pooled query counts per cell
SHARDED_MESH_ROWS = (1, 2, 4, 8)  # sharded lane mesh row-axis sweep (ISSUE 7)
SHARDED_Q = (8, 64)               # pooled query counts per sharded cell
EXPR_DEPTHS = (2, 3)            # expression lane DAG depths (ISSUE 8)
EXPR_Q = (8, 64)                # expression pool sizes per cell
SERVING_RATES = (0.5, 2.0, 4.0)  # serving lane arrival-rate multiples of
#                                  the measured sustainable rate (ISSUE 10)
SERVING_N = 400                  # arrivals per sweep cell
OLAP_Q = (8, 32)                 # fused analytics pool sizes (ISSUE 15)


def load_cpu_baseline(dataset: str) -> tuple[float | None, dict]:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baselines", "cpu_baseline.json")
    if not os.path.exists(path):
        return None, {}
    with open(path) as f:
        data = json.load(f)
    row = data.get("datasets", {}).get(dataset, {}).get("wide_or")
    if not row:
        return None, {}
    return row["ns_per_op_avg"] / 1e9, {
        "source": "baselines/cpu_baseline.json (C++ -O3, "
                  "ParallelAggregation.or algorithm, single thread)",
        "cpu_result_cardinality": row["result_cardinality"],
        "reps": row["reps"],
    }


def _timed_pack(inputs, cls) -> tuple[float, object]:
    # layout pinned dense: layout="auto" (the build-time default since
    # ISSUE 5) flips counts-resident on inflation-heavy shapes, which has
    # no `words` image and would break cross-round lane comparability
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        d = cls(inputs, layout="dense")
        d.words.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best, d


def ingest_phase(name: str) -> dict:
    """Everything that must run BEFORE the process's first device->host
    readback: build + pack timings in the tunnel's pipelined regime.

    Measured tunnel artifact (see query_phase's tunnel_rtt_ms): the axon
    tunnel acks host->device puts asynchronously until the first D2H
    readback, after which EVERY put pays a real ~100-180 ms round trip for
    the remainder of the process.  Ingest cost is therefore measured first,
    in the pipelined regime — which is also the regime a locally-attached
    TPU (PCIe/ICI, no tunnel) runs in all the time.  The post-readback
    number is reported too (pack_ms_post_readback), nothing is hidden.
    """
    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.ops import packing
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.utils import datasets

    if datasets.has_dataset(name):
        arrs = datasets.load_value_arrays(name)
        dataset = name
    else:
        dataset = "synthetic"
        rng = np.random.default_rng(0)
        arrs = [rng.integers(0, 1 << 24, 50000).astype(np.uint32)
                for _ in range(200)]

    bitmaps = [RoaringBitmap.from_values(a) for a in arrs]
    oracle_card = int(np.unique(np.concatenate(arrs)).size)

    # cold build: compiles the densify program for this shape (one-time per
    # shape per cache state — the persistent compilation cache set up in
    # main() makes this ~1s warm vs ~17s on a cold cache)
    t0 = time.perf_counter()
    ds = DeviceBitmapSet(bitmaps, layout="dense")  # pinned, see _timed_pack
    ds.words.block_until_ready()
    t_compile = time.perf_counter() - t0

    t_pack, _ = _timed_pack(bitmaps, DeviceBitmapSet)

    # byte-path ingest (serialized blobs -> HBM, no Container objects):
    # the stream->HBM capability VERDICT r2 item 3 names
    blobs = [b.serialize() for b in bitmaps]
    t0 = time.perf_counter()
    packing.pack_blocked_compact(blobs)
    t_pack_host = time.perf_counter() - t0  # host stream build alone
    t_pack_bytes, ds_bytes = _timed_pack(blobs, DeviceBitmapSet)

    return {
        "dataset": dataset, "bitmaps": bitmaps, "blobs": blobs,
        "oracle_card": oracle_card, "ds": ds, "ds_bytes": ds_bytes,
        "t_compile": t_compile, "t_pack": t_pack,
        "t_pack_bytes": t_pack_bytes, "t_pack_host": t_pack_host,
    }


def query_phase(state: dict, profile: bool) -> dict:
    import jax

    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet

    dataset = state["dataset"]
    bitmaps, oracle_card = state["bitmaps"], state["oracle_card"]
    ds, ds_bytes = state["ds"], state["ds_bytes"]

    # ---- CPU baseline (dataset-specific; never applied to the synthetic
    # fallback workload)
    cpu_s, cpu_info = (load_cpu_baseline(dataset) if dataset != "synthetic"
                       else (None, {}))
    if cpu_s is None:
        t0 = time.perf_counter()
        acc = bitmaps[0].clone()
        for b in bitmaps[1:]:
            acc.ior(b)
        cpu_s = time.perf_counter() - t0
        assert acc.cardinality == oracle_card, "host fold parity failure"
        cpu_info = {"source": "python host fold (no cpu_baseline.json — "
                              "NOT an optimized baseline)"}
    else:
        assert cpu_info.pop("cpu_result_cardinality") == oracle_card, \
            "C++ baseline cardinality drift"

    # first query = the process's first D2H readback for this dataset
    t0 = time.perf_counter()
    _, cards0 = ds.aggregate_device("or", engine="xla")
    total0 = int(np.asarray(cards0.sum()))
    t_first_query = time.perf_counter() - t0
    assert total0 == oracle_card, "device parity failure (single shot)"
    _, c_b = ds_bytes.aggregate_device("or", engine="xla")
    assert int(np.asarray(c_b.sum())) == oracle_card, "byte-path parity"
    ds_bytes = None            # drop BOTH references so the dense image
    state["ds_bytes"] = None   # actually leaves HBM before the packs below

    # tunnel artifact, quantified: one post-readback put of the byte streams
    t_pack_post, _ = _timed_pack(state["blobs"], DeviceBitmapSet)

    # ---- steady state per engine: marginal chained cost
    r1, r2 = R1, R2

    def chained_seconds(engine: str, reps: int) -> float:
        """Best-of-3 timed runs of one compiled chained program (tunnel RTT
        adds per-dispatch noise; min is the noise-robust estimator)."""
        expected = (reps * oracle_card) % 2**32  # uint32 accumulator
        fn = ds.chained_wide_or(reps, engine=engine)
        best = float("inf")
        for i in range(6):  # first call compiles + warms up, then 5 timed
            t0 = time.perf_counter()
            total = int(np.asarray(fn(ds.words)))
            dt = time.perf_counter() - t0
            assert total == expected, \
                f"device parity failure ({engine}): {total} != " \
                f"({reps}*{oracle_card}) mod 2^32"
            if i:
                best = min(best, dt)
        return best

    def marginal(engine: str) -> tuple[float, float]:
        """(steady-state s/op, end-to-end s/op at r2 incl. one dispatch)."""
        for _ in range(4):  # retry when scheduling noise makes t2 <= t1
            t1, t2 = chained_seconds(engine, r1), chained_seconds(engine, r2)
            if t2 > t1:
                return (t2 - t1) / (r2 - r1), t2 / r2
        raise RuntimeError(
            f"unstable timing for engine {engine}: t({r2}) <= t({r1})")

    with (jax.profiler.trace("/tmp/rb_tpu_trace") if profile
          else contextlib.nullcontext()):
        per_engine = {eng: marginal(eng) for eng in ("xla", "pallas")}

    engine = min(per_engine, key=lambda e: per_engine[e][0])
    dev_s, e2e_s = per_engine[engine]

    return {
        "dataset": dataset,
        "ops_per_sec": round(1.0 / dev_s, 3),
        "vs_baseline": round(cpu_s / dev_s, 3),
        "engine": engine,
        "block": ds.block,
        "marginal_us_per_wide_or": {
            k: round(v[0] * 1e6, 2) for k, v in per_engine.items()},
        "e2e_us_per_wide_or_with_dispatch": {
            k: round(v[1] * 1e6, 2) for k, v in per_engine.items()},
        "n_bitmaps": len(bitmaps), "result_cardinality": oracle_card,
        "pack_ms": round(state["t_pack"] * 1e3, 2),
        "pack_from_serialized_bytes_ms": round(state["t_pack_bytes"] * 1e3, 2),
        "pack_host_stream_build_ms": round(state["t_pack_host"] * 1e3, 2),
        "pack_ms_post_readback": round(t_pack_post * 1e3, 2),
        "tunnel_note": "pack_ms rows are measured before the process's first "
                       "device->host readback; after one readback the axon "
                       "tunnel serializes every host->device put at ~100-180 "
                       "ms RTT (pack_ms_post_readback) — a harness artifact, "
                       "not an ingest cost (local PCIe attach has no tunnel)",
        "r4_methodology_note": "cross-round marginal comparisons carry "
                       "caveats. (1) These working sets fit v5e VMEM "
                       "(128 MB), so per-op times can legitimately beat "
                       "HBM bandwidth and shift between rounds with "
                       "compiler scheduling (wikileaks r03 2.0 us vs r04 "
                       "11.3 us per op; both runs bit-exact on the chained "
                       "parity assert). (2) The r03 compact-layout cell "
                       "(31 us) WAS an artifact — its stream operands were "
                       "jit constants and the rebuild got hoisted; "
                       "measured honestly in r04 it is ms-scale "
                       "(realdata_r04 compact cells). The conservative "
                       "barrier-chained cross-checks in realdata_r04 "
                       "bound the dense per-op cost from above.",
        "serialized_mb": round(
            sum(len(x) for x in state["blobs"]) / 1e6, 2),
        "ingest_compile_ms_one_time": round(state["t_compile"] * 1e3, 2),
        "first_query_ms": round(t_first_query * 1e3, 2),
        "cpu_wide_or_ms": round(cpu_s * 1e3, 4),
        "cpu_baseline": cpu_info,
        "hbm_resident_mb": round(ds.hbm_bytes() / 1e6, 1),
        "chained_reps": [r1, r2],
    }


def best_of(fn, reps: int = 5) -> float:
    """Min-of-reps wall time after one warm/compile call — the shared
    timing policy of every QPS lane (batched, fault, multiset)."""
    fn()  # warm / compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def batched_phase(state: dict) -> dict:
    """Batched multi-query lane: queries/sec at Q in BATCH_SIZES over the
    resident set — the dispatch-floor amortization the wide path was bound
    by (BENCH_r05: ~10 us/op marginal vs 35-81 us dispatch overhead).

    Methodology: Q mixed-op random-subset queries run as ONE dispatch
    (BatchEngine.execute) vs one-query-per-dispatch sequential execution;
    q{Q}_e2e_qps includes the dispatch, q{Q}_steady_qps is the chained
    marginal ((t2-t1)/(r2-r1) batches) with the summed-cardinality parity
    invariant asserted on every chained run.  Before any timing, the batch
    results are asserted bit-equal to sequential single-query dispatches.
    """
    from roaringbitmap_tpu.obs import memory as obs_memory
    from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                         random_query_pool)

    ds = state["ds"]
    pool = random_query_pool(ds.n, max(BATCH_SIZES))
    eng = BatchEngine(ds)

    # parity first: the batch must equal one-query-per-dispatch execution
    probe = pool[:32]
    seq = [int(eng.cardinalities([q])[0]) for q in probe]
    got = eng.cardinalities(probe).tolist()
    assert got == seq, "batch/sequential cardinality divergence"

    out: dict = {"parity_checked_queries": len(probe),
                 "mixed_ops": ["or", "xor", "and", "andnot"]}
    t_q1 = best_of(lambda: eng.cardinalities(pool[:1]))
    out["q1_seq_dispatch_qps"] = round(1.0 / t_q1, 1)
    for q in BATCH_SIZES[1:]:
        t = best_of(lambda q=q: eng.cardinalities(pool[:q]))
        out[f"q{q}_e2e_qps"] = round(q / t, 1)
        hbm = obs_memory.dispatch_memory_cell(eng.last_dispatch_memory)
        if hbm:
            # predicted vs measured dispatch HBM (full doc only; the
            # stdout summary line never carries these)
            out[f"q{q}_hbm"] = hbm
        # chained steady state: marginal seconds per batch
        expected = sum(int(c) for c in eng.cardinalities(pool[:q]))
        fns = {r: eng.chained_cardinality(pool[:q], r) for r in BATCH_R}

        def timed(r):
            want = (r * expected) % 2**32
            best = float("inf")
            for i in range(4):
                t0 = time.perf_counter()
                total = int(np.asarray(fns[r]()))
                dt = time.perf_counter() - t0
                assert total == want, f"chained batch parity (Q={q}, r={r})"
                if i:
                    best = min(best, dt)
            return best
        for _ in range(4):
            t1, t2 = timed(BATCH_R[0]), timed(BATCH_R[1])
            if t2 > t1:
                per_batch = (t2 - t1) / (BATCH_R[1] - BATCH_R[0])
                out[f"q{q}_steady_qps"] = round(q / per_batch, 1)
                break
    amort = out.get("q64_e2e_qps", 0.0) / out["q1_seq_dispatch_qps"]
    out["q64_vs_q1_amortization_x"] = round(amort, 2)
    out["meets_5x"] = amort >= 5.0
    out["fault_lane"] = fault_lane_phase(eng, pool)
    out.update(cost_slo_cell(eng, pool))
    return out


def cost_slo_cell(eng, pool) -> dict:
    """Cost/SLO lane (ISSUE 6): one phase-attributed execute of the max-Q
    batch — per-phase wall breakdown (obs.slo) and the dispatch's
    roofline position (obs.cost) — so the round artifact records WHERE
    the batched lane's time goes and how close the launch runs to the
    peak table, and the sentry can trend both."""
    from roaringbitmap_tpu.obs import slo as obs_slo

    q = min(max(BATCH_SIZES), len(pool))
    with obs_slo.attribution():
        eng.cardinalities(pool[:q])
    out: dict = {}
    lq = obs_slo.last_query
    if lq and lq.get("phases_ms"):
        out["phase_ms"] = {ph: v for ph, v in lq["phases_ms"].items()
                           if v >= 0.005 or ph in ("dispatch", "sync")}
    cost = eng.last_dispatch_cost or {}
    if "roofline_fraction" in cost:
        out["cost"] = {
            "roofline_fraction": cost["roofline_fraction"],
            "achieved_gbps": round(cost["achieved_bytes_per_s"] / 1e9, 3),
            "device_ms": cost["device_ms"]}
    return out


def fault_lane_phase(eng, pool) -> dict:
    """Degraded-mode QPS probe (ISSUE 2): the same Q-query batch measured
    (a) clean, (b) with the top engine rung killed by an injected lowering
    fault (the guard demotes one rung down the chain), and (c) with EVERY
    device rung killed (the guard lands on the CPU sequential reference).
    The ratios quantify what a production incident costs in throughput —
    degradation is availability-preserving and bit-exact by construction,
    so throughput is the only axis that moves."""
    import jax

    from roaringbitmap_tpu.runtime import faults

    q = min(64, len(pool))
    batch = pool[:q]
    clean = [r.cardinality for r in eng.execute(batch)]
    t_clean = best_of(lambda: eng.cardinalities(batch), reps=3)
    top = "pallas" if jax.default_backend() == "tpu" else "xla"
    with faults.inject(f"lowering@{top}=1.0:0xFA"):
        demoted = [r.cardinality for r in eng.execute(batch)]
        t_demoted = best_of(lambda: eng.cardinalities(batch), reps=3)
    with faults.inject("lowering=1.0:0xFB"):
        floor = [r.cardinality for r in eng.execute(batch)]
        t_floor = best_of(lambda: eng.cardinalities(batch), reps=3)
    assert demoted == clean and floor == clean, \
        "degraded lanes must stay bit-exact"
    return {
        "q": q, "top_rung": top,
        "qps_clean": round(q / t_clean, 1),
        "qps_demoted_one_rung": round(q / t_demoted, 1),
        "qps_sequential_floor": round(q / t_floor, 1),
        "demotion_overhead_x": round(t_demoted / t_clean, 3),
        "sequential_floor_cost_x": round(t_floor / t_clean, 3),
    }


def multiset_phase() -> dict:
    """Cross-tenant pooled lane (ISSUE 5): S resident tenant sets serving
    Q mixed-op queries as ONE pooled launch (MultiSetBatchEngine) vs the
    per-set sequential BatchEngine loop (S launches), at S in MULTISET_S
    x Q in MULTISET_Q — the dispatch-floor amortization repeated one
    level up.  Tenants are small synthetic sets (the serving-front-end
    regime where the launch floor, not per-query work, dominates).  Every
    cell asserts pooled results bit-equal to the per-set loop before any
    timing.  The Q=64 pipelined cell streams 4 pools through the
    double-buffered dispatcher and reports the host-overlap ratio from
    MultiSetBatchEngine.last_pipeline, plus predicted-vs-measured pooled
    dispatch HBM (multiset.memory accounting)."""
    from roaringbitmap_tpu.obs import memory as obs_memory
    from roaringbitmap_tpu.parallel.batch_engine import BatchEngine
    from roaringbitmap_tpu.parallel.multiset import (MultiSetBatchEngine,
                                                     random_multiset_pool)
    from roaringbitmap_tpu.utils import datasets

    out: dict = {"tenant_bitmaps": 8}
    for s in MULTISET_S:
        tenants = [datasets.synthetic_bitmaps(
            8, seed=40 + i, universe=1 << 16, density=0.006)
            for i in range(s)]
        engines = [BatchEngine.from_bitmaps(t, layout="dense")
                   for t in tenants]
        eng = MultiSetBatchEngine(engines)
        for q in MULTISET_Q:
            pool = random_multiset_pool([8] * s, q, seed=0xACE,
                                        max_operands=3)

            def per_set_loop():
                return [engines[g.set_id].execute(list(g.queries),
                                                  engine="auto")
                        for g in pool]

            want = [[r.cardinality for r in rows]
                    for rows in per_set_loop()]
            got = [[r.cardinality for r in rows]
                   for rows in eng.execute(pool)]
            assert got == want, f"pooled/per-set divergence (S={s} Q={q})"
            t_pool = best_of(lambda: eng.execute(pool))
            t_loop = best_of(per_set_loop)
            cell = {"pooled_qps": round(q / t_pool, 1),
                    "per_set_qps": round(q / t_loop, 1),
                    "pooled_vs_per_set_x": round(t_loop / t_pool, 2)}
            if s > 1:
                hbm = obs_memory.dispatch_memory_cell(
                    eng.last_dispatch_memory)
                if hbm:
                    cell["hbm"] = hbm
            out[f"s{s}_q{q}"] = cell
        if s > 1:
            # pipelined dispatcher: stream 4 pools (serving ticks)
            # through one window; the overlap ratio is the hidden
            # fraction of host plan+pack time (multiset.pipeline span)
            pools = [random_multiset_pool([8] * s, max(MULTISET_Q),
                                          seed=200 + i, max_operands=3)
                     for i in range(4)]
            eng.execute_pipelined(pools)          # warm compiles
            best_of(lambda: eng.execute_pipelined(pools), reps=3)
            out[f"s{s}_pipeline"] = dict(eng.last_pipeline)
    s_max, q_max = max(MULTISET_S), max(MULTISET_Q)
    head = out.get(f"s{s_max}_q{q_max}") or {}
    pipe = out.get(f"s{s_max}_pipeline") or {}
    out["headline"] = {
        "pooled_vs_per_set_x": head.get("pooled_vs_per_set_x"),
        "overlap_ratio": pipe.get("overlap_ratio")}
    return out


def expression_phase() -> dict:
    """Expression-DAG fusion lane (ISSUE 8): depth-{2,3} compositional
    expression pools of Q in EXPR_Q, fused into one launch per (bucket,
    op-group) by the expression compiler (parallel.expr) vs the
    node-at-a-time evaluator (one device launch per DAG reduce node,
    host combines — the only way the pre-expression engines served
    compositional traffic).  Resident sets are small (the dispatch-floor
    regime fusion amortizes).  Every cell asserts fused results
    bit-equal to node-at-a-time before timing; launches_saved comes from
    the rb_expr_launches_saved_total counter delta."""
    from roaringbitmap_tpu import obs
    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.parallel.batch_engine import BatchEngine
    from roaringbitmap_tpu.utils import datasets

    bms = datasets.synthetic_bitmaps(8, seed=88, universe=1 << 16,
                                     density=0.006)
    eng = BatchEngine.from_bitmaps(bms, layout="dense")
    out: dict = {"resident_bitmaps": 8}
    for depth in EXPR_DEPTHS:
        for q in EXPR_Q:
            pool = expr.random_expr_pool(8, q, depth=depth,
                                         seed=0xE0 + depth)
            want = [r.cardinality
                    for r in expr.execute_node_at_a_time(eng, pool)]
            snap0 = obs.snapshot()["counters"].get(
                "rb_expr_launches_saved_total", [])
            saved0 = sum(r["value"] for r in snap0)
            got = [r.cardinality for r in eng.execute(pool)]
            assert got == want, \
                f"fused/node-at-a-time divergence (d={depth} Q={q})"
            snap1 = obs.snapshot()["counters"].get(
                "rb_expr_launches_saved_total", [])
            saved = sum(r["value"] for r in snap1) - saved0
            t_fused = best_of(lambda: eng.execute(pool))
            t_node = best_of(
                lambda: expr.execute_node_at_a_time(eng, pool), reps=3)
            out[f"d{depth}_q{q}"] = {
                "fused_qps": round(q / t_fused, 1),
                "node_qps": round(q / t_node, 1),
                "fused_vs_node_x": round(t_node / t_fused, 2),
                "launches_saved": int(saved)}
    # one-kernel hot path cell (ISSUE 11): the SAME depth-2 pool through
    # the megakernel rung — parity-asserted, QPS next to the multi-op
    # fused lowering, and the per-dispatch transient-byte drop from the
    # unified footprint model (the acceptance referee: XLA cost_analysis
    # under-reports pallas programs, so the measured figures ride along
    # flagged, the deterministic model ratio is the gated lane)
    from roaringbitmap_tpu.insights import analysis as insights

    d0, q0 = min(EXPR_DEPTHS), min(EXPR_Q)
    pool = expr.random_expr_pool(8, q0, depth=d0, seed=0xE0 + d0)
    # the multi-op baseline is pinned to an EXPLICIT rung: on TPU
    # engine="auto" resolves expression pools to the megakernel itself,
    # which would turn both the parity assert and multiop_qps into a
    # megakernel self-comparison
    want = [r.cardinality for r in eng.execute(pool, engine="xla")]
    got = [r.cardinality
           for r in eng.execute(pool, engine="megakernel")]
    assert got == want, "megakernel/multi-op divergence"
    mega_cost = dict(eng.last_dispatch_cost or {})
    t_mega = best_of(lambda: eng.execute(pool, engine="megakernel"))
    t_multiop = best_of(lambda: eng.execute(pool, engine="xla"))
    plan = eng.plan(pool)
    b_sigs = [b.signature for b in plan]

    def model_bytes(e):
        total = insights.predict_batch_dispatch_bytes(
            b_sigs, "dense", 0, e)["peak_bytes"]
        return total + insights.predict_expr_dispatch_bytes(
            plan.expr_signature, e)["peak_bytes"]

    # the gated byte-drop ratio measures against the PALLAS multi-op
    # model — the rung the megakernel actually replaces at the ladder
    # top (the xla model carries a doubling-pass scratch block pallas
    # never allocates, which would inflate the win)
    bytes_x = model_bytes("pallas") / max(1, model_bytes("megakernel"))
    out["mega"] = {
        "mega_qps": round(q0 / t_mega, 1),
        "multiop_qps": round(q0 / t_multiop, 1),
        "mega_vs_multiop_x": round(bytes_x, 2),
        "model_bytes": {"megakernel": model_bytes("megakernel"),
                        "multiop_xla": model_bytes("xla"),
                        "multiop_pallas": model_bytes("pallas")},
        "measured_bytes_accessed": mega_cost.get("bytes_accessed"),
        "measured_estimated": bool(mega_cost.get("estimated", False)),
    }
    d_max, q_max = max(EXPR_DEPTHS), max(EXPR_Q)
    head = out.get(f"d{d_max}_q{q_max}") or {}
    out["headline"] = {
        "fused_vs_node_x": head.get("fused_vs_node_x"),
        "launches_saved": head.get("launches_saved"),
        "mega_vs_multiop_x": out["mega"]["mega_vs_multiop_x"]}
    return out


def serving_phase() -> dict:
    """Sustained-throughput serving lane (ISSUE 10): a timed arrival
    stream replayed through the continuous-batching ``ServingLoop`` at
    SERVING_RATES multiples of the measured sustainable rate — per-cell
    p50/p99 request latency, SLO attainment of the served (non-shed)
    queries, and the shed rate.  The 4x cell runs twice: shedding ON
    (the graceful-degradation claim: survivors stay inside their SLO)
    and shedding OFF (the control: attainment collapses, proving the
    ladder earns its keep rather than overload merely being bad).
    Served results are parity-sampled against the per-set sequential
    reference every cell.  Arrival gaps ride the fault clock, so the
    sweep costs execute time, not wall-clock idle."""
    import numpy as np

    from roaringbitmap_tpu.parallel import BatchQuery, MultiSetBatchEngine
    from roaringbitmap_tpu.runtime import faults, guard
    from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                           ServingRequest)
    from roaringbitmap_tpu.utils import datasets

    s, per_tenant, pool_target = 4, 8, 16
    tenants = [datasets.synthetic_bitmaps(
        per_tenant, seed=70 + i, universe=1 << 16, density=0.006)
        for i in range(s)]
    engine = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
    nosleep = guard.GuardPolicy(backoff_base=0.0, sleep=lambda _s: None)

    # bounded shape vocabulary — the prepared-statement serving pattern:
    # real front-ends reissue a finite query-template set, and the plan/
    # program caches (plus warmup) exist for exactly that; fully random
    # operand subsets would instead measure one compile per pool
    shapes = [("or", (0, 1, 2)), ("and", (1, 2, 3)), ("xor", (0, 2, 4)),
              ("andnot", (0, 1, 3)), ("or", (3, 4)), ("and", (0, 5))]

    def requests(n, seed):
        rng = np.random.default_rng(seed)
        return [ServingRequest(
            int(rng.integers(s)),
            BatchQuery(*shapes[int(rng.integers(len(shapes)))]),
            tenant=f"t{int(rng.integers(s))}")
            for _ in range(n)]

    def fresh_loop(**kw) -> ServingLoop:
        kw.setdefault("pool_target", pool_target)
        kw.setdefault("guard", nosleep)
        kw.setdefault("max_queue", 4096)
        return ServingLoop(engine, ServingPolicy(**kw))

    # warm the shape vocabulary at BOTH pool targets (the overload
    # ladder halves the target, which is a distinct program shape —
    # compiling it mid-incident would be the cold path the warmup story
    # exists to kill), then calibrate the SUSTAINABLE rate through the
    # loop itself (admission + assembly + dispatch + SLO accounting
    # included — engine-only probes undercount the path)
    for tgt in (pool_target, max(1, pool_target // 2)):
        w = fresh_loop(pool_target=tgt, default_deadline_ms=600_000.0)
        # representative-traffic warm (what a production boot replays):
        # pool PROGRAMS key on per-set referenced-row counts, so only
        # traffic-shaped pools cover the signatures the sweep will hit
        w.replay((0.0, r) for r in requests(SERVING_N, 300 + tgt))
    warm = fresh_loop(default_deadline_ms=600_000.0)
    n_cal = pool_target * 8
    t0 = faults.clock()
    warm.replay((0.0, r) for r in requests(n_cal, 2))
    t_per_q = (faults.clock() - t0) / n_cal
    sustainable_qps = 1.0 / t_per_q
    # deadline: several pool-times of headroom — roomy at <= 1x load,
    # unmeetable for stale arrivals under sustained overload
    deadline_ms = max(20.0, 8 * pool_target * t_per_q * 1e3)

    def sweep(rate: float, shed: bool, seed: int) -> dict:
        # slack_x 3: the shed rule judges against predicted execute
        # time, and CPU-proxy pool walls swing ~2x with scheduling —
        # survivors must clear their SLO with margin, not sit on its edge
        loop = fresh_loop(default_deadline_ms=deadline_ms, shed=shed,
                          slack_x=3.0)
        gap = 1.0 / (sustainable_qps * rate)
        reqs = requests(SERVING_N, seed)
        t0 = faults.clock()
        tickets = loop.replay((i * gap, r) for i, r in enumerate(reqs))
        span_s = faults.clock() - t0
        served = [t for t in tickets if t.ok]
        # parity sample: served answers vs the sequential reference
        for t in served[:: max(1, len(served) // 24)]:
            ref = engine._engines[t.request.set_id]._sequential_one(
                t.query)
            assert t.result.cardinality == ref.cardinality, \
                f"serving parity failure at rate {rate}x"
        walls = sorted(t.wall_ms for t in served)
        attained = sum(1 for t in served if not t.missed)
        n = len(tickets)
        return {
            "arrival_x": rate, "shed_enabled": shed,
            "served": len(served),
            "shed_rate": round(
                sum(t.status == "shed" for t in tickets) / n, 4),
            "rejected": sum(t.status == "rejected" for t in tickets),
            "served_qps": round(len(served) / max(span_s, 1e-9), 1),
            "p50_ms": round(walls[len(walls) // 2], 3) if walls else None,
            "p99_ms": round(walls[int(len(walls) * 0.99)], 3)
            if walls else None,
            "slo_attainment": round(attained / max(1, len(served)), 4),
            "degrade_level_peak": loop.level_peak,
        }

    out: dict = {
        "tenants": s, "pool_target": pool_target,
        "sustainable_qps": round(sustainable_qps, 1),
        "deadline_ms": round(deadline_ms, 3),
    }
    for i, rate in enumerate(SERVING_RATES):
        key = f"x{rate:g}".replace(".", "_")
        out[key] = sweep(rate, shed=True, seed=100 + i)
    # the control arm runs at the SAME rate as the overload headline —
    # the collapse proof must be apples-to-apples
    top = SERVING_RATES[-1]
    ctrl_key = f"x{top:g}_noshed".replace(".", "_")
    out[ctrl_key] = sweep(top, shed=False, seed=200)
    over, ctrl = out[f"x{top:g}".replace(".", "_")], out[ctrl_key]
    out["headline"] = {
        "overload_attainment": over["slo_attainment"],
        "noshed_attainment": ctrl["slo_attainment"],
        "meets_90": over["slo_attainment"] >= 0.90,
        "shed_rate": over["shed_rate"],
    }
    return out


def mutation_phase() -> dict:
    """Mutable-tenant lane (ISSUE 12, docs/MUTATION.md): two cells.

    (a) delta-vs-repack: a warmed single-segment ``apply_delta`` against
    a resident N=32 set, vs the full re-pack of the same (updated)
    sources — the five-orders-of-magnitude asymmetry ROADMAP item 1
    names, pinned as ``delta_vs_repack_x``.  (b) cache-vs-recompute: a
    repeated depth-2 expression trace replayed through a result-cached
    engine vs the recompute path (identical engine, no cache), bit-exact
    asserted before timing — ``cache_vs_recompute_x`` is the
    repeated-expression serving claim."""
    from roaringbitmap_tpu.mutation import ResultCache
    from roaringbitmap_tpu.parallel import expr as expr_mod
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.batch_engine import BatchEngine
    from roaringbitmap_tpu.utils import datasets

    out: dict = {}
    # big enough that the full re-pack is honest work (~8M values, a
    # 116 MiB dense image): the delta's wall is a flat ~0.4 ms of host
    # planning + dispatch overhead regardless of set size, which is the
    # whole asymmetry being measured
    bms = datasets.synthetic_bitmaps(64, seed=90, universe=1 << 25,
                                     density=0.03)
    ds = DeviceBitmapSet(bms, layout="dense")
    ds.warmup_delta(1)
    ds.apply_delta(adds={0: [1]})        # warm the whole patch path
    counter = [1]

    def one_delta():
        counter[0] += 1
        rep = ds.apply_delta(adds={0: [counter[0]]})
        assert rep["mode"] == "patch", rep

    t_delta = best_of(one_delta)
    hosts = ds.host_bitmaps()
    t_repack = best_of(lambda: DeviceBitmapSet(hosts, layout="dense"),
                       reps=3)
    # bit-exactness of the patched resident vs the re-packed one
    patched = ds.aggregate("or")
    assert DeviceBitmapSet(hosts, layout="dense").aggregate("or") \
        == patched, "delta-patched set diverged from a fresh re-pack"
    out["delta"] = {"sets": 64, "delta_ms": round(t_delta * 1e3, 4),
                    "repack_ms": round(t_repack * 1e3, 2),
                    "delta_vs_repack_x": round(t_repack / t_delta, 1)}

    trace = expr_mod.random_expr_pool(16, 32, depth=3, seed=9)
    bms2 = datasets.synthetic_bitmaps(16, seed=91, universe=1 << 20,
                                      density=0.02)
    recompute = BatchEngine(DeviceBitmapSet(bms2, layout="dense"),
                            result_cache=None)
    cached = BatchEngine(DeviceBitmapSet(bms2, layout="dense"),
                         result_cache=ResultCache(128 << 20))
    ref = [r.cardinality for r in recompute.execute(trace)]
    got = [r.cardinality for r in cached.execute(trace)]
    assert got == ref, "cached expression replay diverged"
    t_recompute = best_of(lambda: recompute.execute(trace), reps=3)
    t_cached = best_of(lambda: cached.execute(trace), reps=3)
    out["cache"] = {
        "trace_q": len(trace),
        "recompute_qps": round(len(trace) / t_recompute, 1),
        "cached_qps": round(len(trace) / t_cached, 1),
        "cache_vs_recompute_x": round(t_recompute / t_cached, 1),
        "cache_stats": cached.result_cache.stats()}
    out["headline"] = {
        "delta_vs_repack_x": out["delta"]["delta_vs_repack_x"],
        "cache_vs_recompute_x": out["cache"]["cache_vs_recompute_x"]}
    return out


def lattice_phase() -> dict:
    """Closed-lattice lane (ISSUE 13, docs/LATTICE.md): a replayed
    diverse-tenant trace (>= 32 distinct pool shapes over 6 tenants —
    varied op mixes, operand rungs, result forms, tenant subsets) cold
    vs against a warmed lattice.  The cold arm measures what PR 10 named
    as debt: every novel pool composition compiles, so p99 tracks
    traffic novelty.  The warmed arm pre-compiles the whole profile
    vocabulary and must then compile NOTHING: compile count, escapes,
    p50/p99 pool walls, and the padding byte fraction (the price of the
    bounded vocabulary) are the lane's cells; ``lattice_p99_over_p50``
    and ``lattice_escapes`` are the acceptance headline.  Bit-exactness
    cold-vs-warmed is asserted before any timing is reported."""
    import numpy as np

    from roaringbitmap_tpu.obs import metrics as obs_metrics
    from roaringbitmap_tpu.parallel import (BatchGroup, BatchQuery,
                                            MultiSetBatchEngine)
    from roaringbitmap_tpu.runtime import lattice as rt_lattice
    from roaringbitmap_tpu.utils import datasets

    compile_misses = obs_metrics.compile_miss_total

    s, per_tenant = 6, 8
    tenants = [datasets.synthetic_bitmaps(
        per_tenant, seed=130 + i, universe=1 << 16, density=0.006)
        for i in range(s)]
    rng = np.random.default_rng(0x1A77)
    ops = ("or", "and", "xor", "andnot")
    pools, shapes = [], set()
    # SIZE-uniform, SHAPE-diverse: every pool is 3 tenants x 4 queries,
    # but tenant subsets, op mixes, operand subsets, and result forms
    # all vary — that is exactly the novelty dimension the lattice
    # closes, while uniform size keeps the p50/p99 walls comparable
    # (pool size would otherwise leak dispatch-floor amortization into
    # the percentile ratio)
    for _ in range(48):
        sids = rng.choice(s, size=3, replace=False)
        pool = []
        for sid in sids:
            qs = []
            for _q in range(4):
                k = int(rng.integers(2, 7))
                qs.append(BatchQuery(
                    ops[int(rng.integers(4))],
                    tuple(int(x) for x in rng.choice(per_tenant, size=k,
                                                     replace=False)),
                    form=("bitmap" if rng.integers(4) == 0
                          else "cardinality")))
            pool.append(BatchGroup(int(sid), qs))
        pools.append(pool)
        shapes.add(tuple((g.set_id, q.op, q.operands, q.form)
                         for g in pool for q in g.queries))
    assert len(shapes) >= 32, \
        f"diverse trace needs >= 32 distinct pool shapes, got " \
        f"{len(shapes)}"
    sizes = [sum(len(g.queries) for g in pool) for pool in pools]

    def pcts(walls):
        """Per-QUERY p50/p99 over the replayed pools.  Pool sizes are
        uniform by construction (see above), so this is a constant
        rescale into per-query units — kept that way deliberately: if
        the trace ever re-gains varied sizes, raw pool walls would
        measure workload heterogeneity (dispatch-floor amortization),
        not the latency stability the p99/p50 pin is about."""
        walls = sorted(w / n for w, n in zip(walls, sizes))
        return (round(walls[len(walls) // 2], 3),
                round(walls[int(len(walls) * 0.99)], 3))

    def replay(engine):
        walls, cards = [], []
        for pool in pools:
            t0 = time.perf_counter()
            rows = engine.execute(pool)
            walls.append((time.perf_counter() - t0) * 1e3)
            cards.append([[r.cardinality for r in row] for row in rows])
        return walls, cards

    # cold control: no lattice, every novel composition compiles
    rt_lattice.deactivate()
    cold_eng = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                    layout="dense")
    m0 = compile_misses()
    cold_walls, cold_cards = replay(cold_eng)
    cold_compiles = compile_misses() - m0
    cold_p50, cold_p99 = pcts(cold_walls)

    # warmed lattice: the whole vocabulary pre-compiles, then seals
    profile = "q=16,;rows=8,;keys=1,;heads=both;pool=8,"
    warm_eng = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                    layout="dense")
    t0 = time.perf_counter()
    rep = warm_eng.warmup(profile=profile)
    warm_ms = (time.perf_counter() - t0) * 1e3
    m0 = compile_misses()
    pad_fracs = []
    warm_cards = []
    # pass 1: every shape NOVEL to this process — zero compiles is the
    # lattice claim; walls here still pay one-time host planning
    first_walls = []
    for pool in pools:
        t1 = time.perf_counter()
        rows = warm_eng.execute(pool)
        first_walls.append((time.perf_counter() - t1) * 1e3)
        warm_cards.append([[r.cardinality for r in row] for row in rows])
        mem = warm_eng.last_dispatch_memory or {}
        if "lattice_padding_fraction" in mem:
            pad_fracs.append(mem["lattice_padding_fraction"])
    assert warm_cards == cold_cards, \
        "warmed-lattice replay diverged from the cold control"
    # passes 2..4: the steady state the acceptance pin names — a
    # serving front-end reissues its template set, so plans are cache
    # hits and the wall is the dispatch path alone (3 passes = 144
    # samples, so p99 is a percentile rather than a single blip)
    warm_walls, steady_sizes = [], []
    for _ in range(3):
        w, _ = replay(warm_eng)
        warm_walls.extend(w)
        steady_sizes.extend(sizes)
    # compile/escape accounting covers the WHOLE warmed replay — the
    # novel first pass AND the steady passes the headline walls come
    # from (a compile anywhere in it would falsify the claim)
    warm_compiles = compile_misses() - m0
    escapes = rt_lattice.escape_total()
    warm_pq = sorted(w / n for w, n in zip(warm_walls, steady_sizes))
    warm_p50 = round(warm_pq[len(warm_pq) // 2], 3)
    warm_p99 = round(warm_pq[int(len(warm_pq) * 0.99)], 3)
    first_p50, first_p99 = pcts(first_walls)
    rt_lattice.deactivate()
    out = {
        "tenants": s, "pools": len(pools),
        "distinct_shapes": len(shapes),
        "profile": profile,
        "warmup_ms": round(warm_ms, 1),
        "points": rep["lattice"]["points"],
        "cold": {"compiles": cold_compiles, "p50_ms": cold_p50,
                 "p99_ms": cold_p99,
                 "p99_over_p50": round(cold_p99 / max(cold_p50, 1e-9),
                                       2)},
        "warmed": {"compiles": warm_compiles, "escapes": escapes,
                   "first_pass_p50_ms": first_p50,
                   "first_pass_p99_ms": first_p99,
                   "p50_ms": warm_p50, "p99_ms": warm_p99,
                   "padding_fraction": round(max(pad_fracs or [0.0]),
                                             4)},
    }
    out["headline"] = {
        "lattice_escapes": escapes,
        "compiles_cold": cold_compiles,
        "compiles_warm": warm_compiles,
        "lattice_p99_over_p50": round(warm_p99 / max(warm_p50, 1e-9), 2),
        "meets_2x": warm_p99 <= 2.0 * warm_p50,
        "padding_byte_fraction": out["warmed"]["padding_fraction"],
        "zero_compile_steady_state": warm_compiles == 0 and escapes == 0,
    }
    return out


def olap_phase() -> dict:
    """Device-native analytics lane (ISSUE 15, docs/ANALYTICS.md): fused
    filter-then-aggregate OLAP pools — ``sum_`` / ``top_k`` roots over
    set-algebra x value-predicate found sets — in ONE engine launch, vs
    the TWO-PHASE baseline the lane replaces (filter dispatch, bitmap
    readback, re-densify over the column keys, second aggregate
    dispatch; ``analytics.two_phase_execute``).  Every cell asserts the
    fused pool bit-equal to the two-phase run AND the host
    BSI/RangeBitmap oracle before timing; ``fused_vs_twophase_x`` is the
    acceptance headline (>= 2x on the CPU proxy).  The warmed sub-cell
    replays the same traffic with NEW predicate values through a sealed
    ``bsi=<depth>`` lattice and must compile NOTHING (zero escapes) —
    the zero-post-warmup-compile half of the acceptance pin."""
    import numpy as np

    from roaringbitmap_tpu.analytics import BsiColumn, two_phase_execute
    from roaringbitmap_tpu.obs import metrics as obs_metrics
    from roaringbitmap_tpu.ops.packing import next_pow2
    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.batch_engine import BatchEngine
    from roaringbitmap_tpu.runtime import lattice as rt_lattice
    from roaringbitmap_tpu.utils import datasets

    rng = np.random.default_rng(0x01A9)
    n, uni, vmax = 8, 1 << 16, 9000
    bms = datasets.synthetic_bitmaps(n, seed=150, universe=uni,
                                     density=0.006)
    # result cache OFF on both arms: the lane measures execution, not
    # the mutation cache (which would turn the replay into dict hits)
    eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                      result_cache=None)
    ids = np.unique(rng.integers(0, uni, 20000)).astype(np.uint32)
    col = BsiColumn("price", ids,
                    rng.integers(0, vmax, ids.size).astype(np.int64))
    eng._ds.attach_column(col)

    def pool_of(q: int, seed: int) -> list:
        """Mixed aggregate-rooted OLAP pool: sum_/top_k over fused
        (set-algebra AND value-range) found sets — the
        ``count((A|B) & range_(price, lo, hi))`` class."""
        r = np.random.default_rng(seed)
        out = []
        for i in range(q):
            a, b = r.choice(n, size=2, replace=False)
            lo = int(r.integers(0, vmax // 2))
            hi = lo + int(r.integers(200, vmax // 2))
            found = expr.and_(expr.or_(int(a), int(b)),
                              expr.range_("price", lo, hi))
            if i % 2:
                out.append(expr.ExprQuery(expr.sum_("price",
                                                    found=found)))
            else:
                out.append(expr.ExprQuery(
                    expr.top_k("price", 8, found=found), form="bitmap"))
        return out

    def results_of(rows) -> list:
        return [(r.cardinality, r.value,
                 None if r.bitmap is None else r.bitmap.cardinality)
                for r in rows]

    out: dict = {"resident_bitmaps": n, "column_rows": int(ids.size),
                 "column_depth_pad": col.depth_pad}
    for q in OLAP_Q:
        pool = pool_of(q, 0xA0 + q)
        fused = eng.execute(pool)
        tp = two_phase_execute(eng, pool)
        assert results_of(fused) == results_of(tp), \
            f"fused/two-phase divergence (Q={q})"
        # host-oracle pin: the fused answers vs the host BSI evaluator
        for qq, r in zip(pool, fused):
            card, value, bm = expr.evaluate_host_agg(
                qq.expr, bms, {"price": col})
            assert (r.cardinality, r.value) == (card, value), q
            if qq.form == "bitmap":
                assert r.bitmap == bm, q
        t_fused = best_of(lambda: eng.execute(pool))
        t_two = best_of(lambda: two_phase_execute(eng, pool), reps=3)
        out[f"q{q}"] = {
            "fused_qps": round(q / t_fused, 1),
            "twophase_qps": round(q / t_two, 1),
            "fused_vs_twophase_x": round(t_two / t_fused, 2)}

    # Megakernel v2 sub-cell: the SAME fused filter-then-aggregate pool
    # forced onto the one-kernel rung (VSCAN/VAGG opcodes) vs the
    # multi-op auto rung — parity-pinned against the auto answers (which
    # the loop above already pinned to the host oracle) before timing
    q_mega = max(OLAP_Q)
    mega_pool = pool_of(q_mega, 0xC7)
    auto_rows = eng.execute(mega_pool)
    mega_rows = eng.execute(mega_pool, engine="megakernel",
                            fallback=False)
    assert results_of(mega_rows) == results_of(auto_rows), \
        "megakernel/auto divergence in the OLAP pool"
    t_auto = best_of(lambda: eng.execute(mega_pool))
    t_mega = best_of(lambda: eng.execute(mega_pool, engine="megakernel",
                                         fallback=False))
    out["mega"] = {"q": q_mega,
                   "mega_qps": round(q_mega / t_mega, 1),
                   "auto_qps": round(q_mega / t_auto, 1),
                   "mega_olap_x": round(t_auto / t_mega, 2)}

    # warmed replay: a sealed bsi=<depth> lattice must serve NEW
    # predicate values / k compile-free (the lattice satellite's claim,
    # mirrored from lattice_phase onto analytics traffic)
    warm_eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                           result_cache=None)
    warm_eng._ds.attach_column(col)
    prof = (f"q=4,;rows={next_pow2(n)};keys=8;"
            f"ops=or,and,xor,andnot;heads=both;expr=2;"
            f"bsi={col.depth_pad},")
    rep = warm_eng.warmup(profile=prof)
    m0 = obs_metrics.compile_miss_total()
    e0 = rt_lattice.escape_total()
    # single-query replay — the prepared-statement pattern the lattice
    # closes over (one OLAP request per arrival): warmed SHAPES, new
    # predicate values / operand pairs / k every iteration
    warm_walls = []
    for i in range(6):
        for q in pool_of(4, 0xB0 + i):
            t0 = time.perf_counter()
            warm_eng.execute([q])
            warm_walls.append((time.perf_counter() - t0) * 1e3)
    warmed_compiles = obs_metrics.compile_miss_total() - m0
    escapes = rt_lattice.escape_total() - e0
    rt_lattice.deactivate()
    out["warmed"] = {
        "profile": prof,
        "points": rep["lattice"]["points"],
        "warmed_compiles": warmed_compiles,
        "escapes": escapes,
        "replay_p50_ms": round(sorted(warm_walls)[len(warm_walls) // 2],
                               3)}
    q_max = max(OLAP_Q)
    out["headline"] = {
        "fused_vs_twophase_x": out[f"q{q_max}"]["fused_vs_twophase_x"],
        "meets_2x": out[f"q{q_max}"]["fused_vs_twophase_x"] >= 2.0,
        "mega_olap_x": out["mega"]["mega_olap_x"],
        "warmed_compiles": warmed_compiles,
        "zero_compile_warmed": warmed_compiles == 0 and escapes == 0}
    return out


def resident_phase() -> dict:
    """Persistent device-resident pool queue lane (Megakernel v2,
    docs/SERVING.md "Resident pump"): steady-state serving replay of
    fused-analytics pools through the descriptor ring vs the SAME
    traffic through the per-pool host-dispatch path.  Both arms run the
    identical warmed/sealed vocabulary; the resident arm additionally
    pins ``rb_serving_dispatches_total`` flat across the whole replay —
    the zero-per-pool-host-dispatch acceptance claim — and every ticket
    is spot-checked against the host oracle.  ``resident_vs_dispatch_x``
    is the headline (> 1 required: descriptor write + stamp poll must
    beat plan-resolve + guarded launch per pool)."""
    import numpy as np

    from roaringbitmap_tpu.analytics import BsiColumn
    from roaringbitmap_tpu.obs import metrics as obs_metrics
    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.multiset import MultiSetBatchEngine
    from roaringbitmap_tpu.runtime import lattice as rt_lattice
    from roaringbitmap_tpu.serving import ServingLoop, ServingPolicy
    from roaringbitmap_tpu.serving.loop import ServingRequest, \
        replay_stream
    from roaringbitmap_tpu.utils import datasets

    def mk_tenant(seed: int, uni: int, vmax: int):
        bms = datasets.synthetic_bitmaps(4, seed=seed, universe=uni,
                                         density=0.004)
        ds = DeviceBitmapSet(bms)
        rng = np.random.default_rng(seed + 1)
        ids = np.unique(rng.integers(0, uni, 4000)).astype(np.uint32)
        col = BsiColumn("price", ids,
                        rng.integers(0, vmax, ids.size).astype(np.int64))
        ds.attach_column(col)
        return bms, ds, col

    # small resident sets on purpose: steady-state serving pools are
    # latency-bound, not bandwidth-bound — the smaller the kernel wall,
    # the larger the share the per-pool host dispatch costs the ring
    # removes (the quantity this lane measures)
    tenants = [mk_tenant(0x51, 1 << 12, 500),
               mk_tenant(0x61, 1 << 11, 120)]
    depth = max(c.depth_pad for _, _, c in tenants)
    prof = (f"q=4,;rows=16,;keys=4,;ops=or,and;heads=both;pool=16,;"
            f"expr=2;bsi={depth},")

    def arrivals_of(n_pools: int) -> list:
        # NEW predicate values every arrival — the prepared-statement
        # replay pattern: the sealed lattice serves fresh values
        # compile-free, and neither arm can hide behind the
        # materialized-result cache
        r = np.random.default_rng(0x16)
        out, t = [], 0.0
        for i in range(2 * n_pools):
            if i % 2:
                q = expr.ExprQuery(expr.sum_(
                    "price", found=expr.and_(
                        expr.or_(0, 1),
                        expr.cmp("price", "ge",
                                 int(r.integers(1, 100))))))
            else:
                q = expr.ExprQuery(expr.and_(
                    expr.or_(0, 1),
                    expr.cmp("price", "le",
                             int(r.integers(50, 450)))))
            out.append((t, ServingRequest(set_id=i % 2, query=q)))
            t += 1e-4
        return out

    n_pools = 64

    def mk_loop(use_resident: bool):
        eng = MultiSetBatchEngine([ds for _, ds, _ in tenants])
        # both arms pin the SAME one-kernel rung: the lane measures the
        # per-pool host-dispatch overhead the ring removes, not a rung
        # choice (the rung comparison is olap_phase's mega sub-cell)
        loop = ServingLoop(eng, ServingPolicy(
            resident=use_resident, pool_target=2,
            engine="megakernel", default_deadline_ms=60000.0))
        loop.warmup(profile=prof)
        return loop

    def one_replay(loop) -> float:
        t0 = time.perf_counter()
        tickets = replay_stream(loop, arrivals_of(n_pools))
        wall = time.perf_counter() - t0
        assert all(t.ok for t in tickets), "resident-lane replay failed"
        # host-oracle spot check on the first pool's tickets
        for t in tickets[:2]:
            bms_x, _, col_x = tenants[t.request.set_id]
            q = t.request.query
            if isinstance(q.expr, expr.Agg):
                card, value, _ = expr.evaluate_host_agg(
                    q.expr, bms_x, {"price": col_x})
                assert (t.result.cardinality, t.result.value) \
                    == (card, value)
            else:
                ref = expr.evaluate_host(q.expr, bms_x,
                                         {"price": col_x})
                assert t.result.cardinality == ref.cardinality
        return wall

    # dispatch arm warmed first (its warmup also warms the jit caches
    # the resident arm shares — biases AGAINST the resident claim),
    # then the replays INTERLEAVE: the pool wall on the CPU proxy is
    # pallas-interpret-dominated and machine jitter exceeds the
    # per-pool overhead under test, so both arms must sample the same
    # conditions; min over reps is the honest floor each arm reaches
    loop_dispatch = mk_loop(False)
    loop_resident = mk_loop(True)

    def dispatch_count() -> int:
        return int(obs_metrics.counter("rb_serving_dispatches_total",
                                       site="serving").value)

    d0 = dispatch_count()
    one_replay(loop_resident)            # resident jit/plan warm pass
    res_dispatches = dispatch_count() - d0
    t_dispatch = t_resident = float("inf")
    disp_dispatches = 0
    for _ in range(5):
        c0 = dispatch_count()
        t_dispatch = min(t_dispatch, one_replay(loop_dispatch))
        c1 = dispatch_count()
        t_resident = min(t_resident, one_replay(loop_resident))
        # every resident replay (warm pass included) must move the
        # dispatch counter ZERO times; the dispatch arm moves it
        # once per pool
        disp_dispatches += c1 - c0
        res_dispatches += dispatch_count() - c1
    res_served = loop_resident._resident.stats["served"]
    rt_lattice.deactivate()
    out = {
        "pools": n_pools,
        "dispatch_arm": {"wall_ms": round(t_dispatch * 1e3, 1),
                         "host_dispatches": disp_dispatches},
        "resident_arm": {"wall_ms": round(t_resident * 1e3, 1),
                         "host_dispatches": res_dispatches,
                         "ring_served": res_served},
    }
    out["headline"] = {
        "resident_vs_dispatch_x": round(t_dispatch / t_resident, 2),
        "zero_host_dispatch": res_dispatches == 0
        and res_served >= n_pools,
    }
    return out


def _dryrun_env(n_devices: int = 8) -> dict:
    """A CPU dry-run environment for subprocess cells: forced host
    platform device count, TPU plugin never initialised (the
    dryrun_multichip pattern — REPLACE, never append, JAX_PLATFORMS)."""
    env = os.environ.copy()
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags
                        + f" --xla_force_host_platform_device_count="
                          f"{n_devices}")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def sharded_phase() -> dict:
    """Mesh-sharded pooled lane (ISSUE 7): ShardedBatchEngine over
    {1,2,4,8}x1 CPU dry-run meshes x Q in SHARDED_Q, pooled QPS +
    per-shard balance vs the single-device MultiSetBatchEngine, plus the
    warm-restart cold-path probe (persistent compile cache, ROADMAP
    item 3).  Runs in a SUBPROCESS with 8 forced host-platform devices —
    the parent process's backend (a real TPU, or a 1-device CPU) cannot
    host the mesh sweep.  CPU-proxy caveat rides in the cell: virtual
    devices share the host cores, so dry-run mesh QPS measures collective
    overhead, not the scaling a real slice shows; parity and balance are
    the gated signals."""
    try:
        # outer budget must dominate the cell's own worst case: the mesh
        # sweep's compiles PLUS warm_restart_probe's two nested 600s
        # subprocesses — a tighter cap would discard the whole lane
        # (sentry-gated) on a slow machine
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sharded-cell"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=2400, env=_dryrun_env(max(SHARDED_MESH_ROWS)),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])
    except Exception as e:
        return {"error": f"sharded cell failed: {type(e).__name__}: {e}"}


def sharded_cell_main() -> None:
    """Subprocess body for sharded_phase (8 forced CPU devices)."""
    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import (BatchEngine,
                                            MultiSetBatchEngine,
                                            ShardedBatchEngine)
    from roaringbitmap_tpu.parallel.multiset import random_multiset_pool

    rng = np.random.default_rng(0x5AAD)
    s = 4
    tenants = [[RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 2000).astype(np.uint32)))
        for _ in range(8)] for _ in range(s)]
    engines = [BatchEngine.from_bitmaps(t, layout="dense")
               for t in tenants]
    single = MultiSetBatchEngine(engines)
    pools = {q: random_multiset_pool([8] * s, q, seed=0xACE,
                                     max_operands=4) for q in SHARDED_Q}
    out: dict = {"tenants": s,
                 "note": ("dry-run mesh: virtual devices share host "
                          "cores, QPS measures collective overhead")}
    single_qps = {}
    for q, pool in pools.items():
        t = best_of(lambda pool=pool: single.execute(pool, engine="xla"))
        single_qps[q] = round(q / t, 1)
        out[f"single_q{q}_qps"] = single_qps[q]
    want = {q: [[r.cardinality for r in rows]
                for rows in single.execute(pools[q], engine="xla")]
            for q in SHARDED_Q}
    for rows in SHARDED_MESH_ROWS:
        mesh = Mesh(np.array(jax.devices()[:rows]).reshape(rows, 1),
                    ("rows", "data"))
        eng = ShardedBatchEngine(engines, mesh=mesh, placement="sharded")
        for q, pool in pools.items():
            got = [[r.cardinality for r in rws]
                   for rws in eng.execute(pool)]
            assert got == want[q], f"sharded parity m{rows}x1 q{q}"
            t = best_of(lambda pool=pool: eng.execute(pool))
            out[f"m{rows}x1_q{q}"] = {
                "pooled_qps": round(q / t, 1),
                "shard_balance": round(eng.shard_balance, 4)}
    q_max = max(SHARDED_Q)
    best_mesh = max((out[f"m{r}x1_q{q_max}"]["pooled_qps"], r)
                    for r in SHARDED_MESH_ROWS)
    out["headline"] = {
        "sharded_vs_single_x": round(
            best_mesh[0] / max(single_qps[q_max], 1e-9), 3),
        "best_mesh_rows": best_mesh[1]}
    out["warm_restart"] = warm_restart_probe()
    print(json.dumps(out))


def warm_restart_probe() -> dict:
    """Cold vs warm process boot against one persistent compile cache
    (ROARING_TPU_COMPILE_CACHE): two fresh subprocesses share a new
    cache dir; the second replays the first's compiles from disk.
    ``warm_restart_x`` = the warm process's first-query wall over its
    steady per-query wall — the ROADMAP item 3 acceptance ratio."""
    cache = tempfile.mkdtemp(prefix="rb_warm_cache_")
    env = _dryrun_env(1)
    env["ROARING_TPU_COMPILE_CACHE"] = cache
    rows = []
    for tag in ("cold", "warm"):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--warm-restart-cell"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                timeout=600, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            rows.append(json.loads(
                proc.stdout.decode().strip().splitlines()[-1]))
        except Exception as e:
            return {"error": f"{tag} run failed: {type(e).__name__}"}
    cold, warm = rows
    return {
        "cold_warmup_ms": cold["warmup_ms"],
        "warm_warmup_ms": warm["warmup_ms"],
        "cold_first_query_ms": cold["first_query_ms"],
        "warm_first_query_ms": warm["first_query_ms"],
        "steady_query_ms": warm["steady_query_ms"],
        "warm_restart_x": round(
            warm["first_query_ms"] / max(warm["steady_query_ms"], 1e-9),
            2),
        "cache_entries": cold.get("cache_entries"),
    }


def warm_restart_cell_main() -> None:
    """Subprocess body for warm_restart_probe: build a small engine,
    warmup(rungs) through the persistent cache, then time the first real
    query and the steady state."""
    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import BatchEngine
    from roaringbitmap_tpu.runtime import warmup as rt_warmup

    rng = np.random.default_rng(3)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 16, 800).astype(np.uint32))
        for _ in range(8)]
    t0 = time.perf_counter()
    eng = BatchEngine.from_bitmaps(bms, layout="dense")
    eng.warmup(rungs=(4,))
    warmup_ms = (time.perf_counter() - t0) * 1e3
    queries = eng._rung_queries(4, ("or", "and", "xor", "andnot"))
    t0 = time.perf_counter()
    eng.cardinalities(queries)
    first_ms = (time.perf_counter() - t0) * 1e3
    steady = best_of(lambda: eng.cardinalities(queries))
    cache_dir = rt_warmup.compile_cache_dir()
    n_entries = (len(os.listdir(cache_dir))
                 if cache_dir and os.path.isdir(cache_dir) else 0)
    print(json.dumps({
        "warmup_ms": round(warmup_ms, 1),
        "first_query_ms": round(first_ms, 3),
        "steady_query_ms": round(steady * 1e3, 3),
        "cache_entries": n_entries}))


def pod_phase() -> dict:
    """Pod-scale serving lane (ISSUE 14, docs/POD.md): two cells.

    (a) A SIMULATED 2-host pod in an 8-device dry-run subprocess —
    pod-vs-single routed QPS over one request stream, the consistent-
    routing overhead per request, and the host-drop recovery wall (fail
    a host with tickets queued, measure until every affected ticket
    re-served from the replica — the ``reroute`` rung's price).

    (b) A REAL 2-process cluster (jax.distributed over localhost, the
    tests/test_multihost.py harness): each process serves exactly its
    routed partition of one fixed stream; the aggregate QPS of the two
    OS processes against a 1-process control is the routing-partitioned
    scale-out the pod front door buys on ANY backend (cross-process
    collective dispatch itself needs a TPU pod — the standing debt)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pod-cell"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=1200, env=_dryrun_env(8),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    except Exception as e:
        out = {"error": f"pod cell failed: {type(e).__name__}: {e}"}
    out["cluster2"] = pod_cluster_probe()
    return out


def pod_cell_main() -> None:
    """Subprocess body for pod_phase's simulated cells (8 CPU devices)."""
    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            MultiSetBatchEngine, podmesh)
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingLoop,
                                           ServingPolicy, ServingRequest)

    rng = np.random.default_rng(0x90D2)
    s = 3
    sets = [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(6)], layout="dense") for _ in range(s)]
    shapes = [("or", (0, 1, 2)), ("and", (1, 2, 3)), ("xor", (0, 2, 4)),
              ("andnot", (0, 1, 3)), ("or", (3, 4)), ("and", (0, 5))]

    def requests(n, seed):
        r = np.random.default_rng(seed)
        return [ServingRequest(
            int(r.integers(s)),
            BatchQuery(*shapes[int(r.integers(len(shapes)))]),
            tenant=f"t{int(r.integers(s))}") for _ in range(n)]

    def policy():
        return ServingPolicy(
            pool_target=8, default_deadline_ms=600_000.0, max_queue=4096,
            guard=guard.GuardPolicy(backoff_base=0.0,
                                    sleep=lambda _s: None))

    n = 192
    single = ServingLoop(MultiSetBatchEngine(sets), policy())
    single.replay((0.0, r) for r in requests(n, 5))       # warm
    t0 = time.perf_counter()
    ts = single.replay((0.0, r) for r in requests(n, 6))
    single_qps = sum(t.ok for t in ts) / (time.perf_counter() - t0)
    pod = podmesh.PodMesh.simulate(2)
    # skewed rates: tenant 0 lands in the replicated-N regime, so the
    # host-drop cell below exercises the replica path, not the single
    # demotion
    plan = podmesh.place(sets, pod, qps=[8.0, 1.0, 1.0])
    fd = PodFrontDoor(sets, pod=pod, plan=plan, policy=policy())
    fd.replay((0.0, r) for r in requests(n, 5))           # warm
    t0 = time.perf_counter()
    ts = fd.replay((0.0, r) for r in requests(n, 6))
    pod_qps = sum(t.ok for t in ts) / (time.perf_counter() - t0)
    assert all(t.ok for t in ts), "pod replay left non-served tickets"
    t0 = time.perf_counter()
    reps = 4096
    for i in range(reps):
        podmesh.route(plan, i % s, (0, 1))
    route_us = (time.perf_counter() - t0) / reps * 1e6
    # host-drop recovery: queue the replicated tenant's traffic on its
    # routed host, drop that host, measure the wall until every ticket
    # re-served from the replica (cold-replica compiles included — that
    # IS the recovery price a real incident pays)
    victim = fd.owner_host(0)
    drop = [fd.submit(ServingRequest(0, BatchQuery(*shapes[i % 4]),
                                     tenant="t0")) for i in range(24)]
    t0 = time.perf_counter()
    fd.fail_host(victim)
    fd.drain()
    recovery_ms = (time.perf_counter() - t0) * 1e3
    assert all(t.ok for t in drop), "host-drop left non-served tickets"
    print(json.dumps({
        "tenants": s, "hosts": 2,
        "regimes": plan.regime_counts(),
        "single_qps": round(single_qps, 1),
        "pod_qps": round(pod_qps, 1),
        "pod_vs_single_x": round(pod_qps / max(single_qps, 1e-9), 3),
        "route_us": round(route_us, 3),
        "host_drop_recovery_ms": round(recovery_ms, 1),
        "reroutes": fd.stats["reroutes"],
        "note": ("simulated pod on one process: virtual hosts share "
                 "the machine, QPS measures routing overhead, not "
                 "scale-out")}))


def pod_cluster_probe() -> dict:
    """The 2-process cluster cell: two jax.distributed workers each
    serving their routed partition of one fixed stream, against a
    1-process control serving all of it."""
    import socket

    def free_port() -> int:
        with socket.socket() as sock:
            sock.bind(("127.0.0.1", 0))
            return sock.getsockname()[1]

    def run_workers(nproc: int):
        port = free_port()
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--pod-worker",
             str(i), str(port), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=_dryrun_env(1),
            cwd=os.path.dirname(os.path.abspath(__file__)))
            for i in range(nproc)]
        rows = []
        for p in procs:
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"pod worker rc={p.returncode}")
            rows.append(json.loads(
                out.decode().strip().splitlines()[-1]))
        return rows

    try:
        pair = run_workers(2)
        solo = run_workers(1)[0]
        agg_qps = round(sum(r["served"] for r in pair)
                        / max(max(r["wall_s"] for r in pair), 1e-9), 1)
        return {
            "bringup_ms": [r["bringup_ms"] for r in pair],
            "served_per_host": [r["served"] for r in pair],
            "pod2_qps": agg_qps,
            "single_qps": solo["qps"],
            "cluster2_vs_single_x": round(
                agg_qps / max(solo["qps"], 1e-9), 3),
            "routes_agree": pair[0]["routes"] == pair[1]["routes"],
        }
    except Exception as e:
        return {"error": f"cluster cell failed: {type(e).__name__}: {e}"}


def pod_worker_main(pid: int, port: str, nproc: int) -> None:
    """Subprocess body for pod_cluster_probe: join the cluster, build
    the shared tenant universe, serve exactly this host's routed share
    of the fixed stream."""
    t_boot = time.perf_counter()
    if nproc > 1:
        from roaringbitmap_tpu.parallel import multihost

        multihost.initialize(f"127.0.0.1:{port}", num_processes=nproc,
                             process_id=pid)
    bringup_ms = (time.perf_counter() - t_boot) * 1e3

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            podmesh)
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                           ServingRequest)

    rng = np.random.default_rng(0x90D3)
    s = 4
    sets = [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 16, 900).astype(np.uint32)))
        for _ in range(6)], layout="dense") for _ in range(s)]
    pod = (podmesh.PodMesh.detect() if nproc > 1
           else podmesh.PodMesh.simulate(1))
    plan = podmesh.place(sets, pod)
    fd = PodFrontDoor(sets, pod=pod, plan=plan, policy=ServingPolicy(
        pool_target=8, default_deadline_ms=600_000.0, max_queue=4096,
        guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda _s: None)))
    shapes = [("or", (0, 1, 2)), ("and", (1, 2, 3)), ("xor", (0, 2)),
              ("andnot", (0, 1, 3))]
    reqs = [ServingRequest(i % s, BatchQuery(*shapes[i % len(shapes)]),
                           tenant=f"t{i % s}") for i in range(240)]
    mine = [r for r in reqs
            if fd.owner_host(r.set_id) in fd._loops]
    for r in mine[:32]:
        fd.submit(r)
    fd.drain()                                            # warm
    t0 = time.perf_counter()
    tickets = [fd.submit(r) for r in mine]
    fd.drain()
    wall = time.perf_counter() - t0
    assert all(t.ok for t in tickets), "pod worker left non-served"
    print(json.dumps({
        "pid": pid, "bringup_ms": round(bringup_ms, 1),
        "served": len(mine), "wall_s": round(wall, 4),
        "qps": round(len(mine) / max(wall, 1e-9), 1),
        "routes": [str(fd.owner_host(i)) for i in range(s)]}))


def durability_phase() -> dict:
    """Durable-tenant lane (ISSUE 17, docs/DURABILITY.md): the write-
    ahead journal's overhead on the delta path (NEUTRAL — durability is
    bought, not free; the lane pins the price), crash-recovery wall vs
    tenant count (snapshot load + journal-tail replay), and a LIVE
    migration under traffic (blip wall + the zero-failed-request pin).
    Runs in an 8-device dry-run subprocess like the pod lane."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--durability-cell"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=1200, env=_dryrun_env(8),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])
    except Exception as e:
        return {"error":
                f"durability cell failed: {type(e).__name__}: {e}"}


def durability_cell_main() -> None:
    """Subprocess body for durability_phase (8 CPU devices)."""
    import shutil
    import tempfile

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.mutation.durability import (DurableTenant,
                                                       FlushPolicy,
                                                       recover_tenant)
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            podmesh)
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                           ServingRequest,
                                           migrate_tenant)

    rng = np.random.default_rng(0xD07A)
    root = tempfile.mkdtemp(prefix="rb_durability_bench_")
    policy = FlushPolicy(mode="batch", every_n=8)

    def mk_ds():
        return DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 16, 1200).astype(np.uint32)))
            for _ in range(4)], layout="dense")

    def deltas(n, seed):
        r = np.random.default_rng(seed)
        return [({int(s): np.unique(r.integers(0, 1 << 16, 24)).tolist()
                  for s in r.integers(0, 4, 2)},
                 {0: r.integers(0, 1 << 16, 4).tolist()})
                for _ in range(n)]

    out: dict = {}
    try:
        # (a) journal overhead: same delta stream, plain vs journaled
        n = 48
        stream = deltas(n, 11)
        plain = mk_ds()
        plain.apply_delta(adds={0: [1]})                      # warm
        t0 = time.perf_counter()
        for a, rm in stream:
            plain.apply_delta(adds=a, removes=rm)
        plain_s = time.perf_counter() - t0
        tenant = DurableTenant(mk_ds(), root=root, tenant="overhead",
                               policy=policy, snapshot_every=None)
        tenant.apply_delta(adds={0: [1]})                     # warm
        t0 = time.perf_counter()
        for a, rm in stream:
            tenant.apply_delta(adds=a, removes=rm)
        durable_s = time.perf_counter() - t0
        tenant.close()
        out["journal"] = {
            "deltas": n, "flush": policy.mode,
            "plain_ms": round(plain_s * 1e3, 2),
            "durable_ms": round(durable_s * 1e3, 2),
            # NEUTRAL: the WAL's price, pinned not gated
            "journal_overhead_x": round(
                durable_s / max(plain_s, 1e-9), 3)}
        # (a') group commit: one fsync covers N tenants' pending
        # appends (FlushPolicy(mode="group"), docs/WIRE.md cross-ref)
        # — fsyncs per applied delta must come in below 1.0 and the
        # WAL's wall price below the solo batch arm above
        from roaringbitmap_tpu import obs as _obs
        from roaringbitmap_tpu.mutation.durability import \
            GroupCommitScheduler

        def _ctr(name):
            return sum(r["value"] for r in
                       _obs.snapshot()["counters"].get(name, []))

        sched = GroupCommitScheduler(every_n=8)
        gts = [DurableTenant(mk_ds(), root=root, tenant=f"grp{i}",
                             policy=sched.policy(),
                             snapshot_every=None) for i in range(4)]
        for t in gts:
            t.apply_delta(adds={0: [1]})                      # warm
        sched.commit()
        f0 = _ctr("rb_journal_fsyncs_total")
        c0 = _ctr("rb_journal_group_commits_total")
        t0 = time.perf_counter()
        for k, (a, rm) in enumerate(stream):
            gts[k % 4].apply_delta(adds=a, removes=rm)
        sched.commit()                         # shutdown barrier
        group_s = time.perf_counter() - t0
        fsyncs = _ctr("rb_journal_fsyncs_total") - f0
        for t in gts:
            t.close()
        out["journal"]["group"] = {
            "tenants": 4, "deltas": n, "fsyncs": fsyncs,
            "group_commits":
                _ctr("rb_journal_group_commits_total") - c0,
            "fsync_per_delta": round(fsyncs / n, 3),
            "group_overhead_x": round(
                group_s / max(plain_s, 1e-9), 3)}
        # (b) recovery wall vs tenant count
        rec = {}
        for count in (1, 4):
            names = []
            for i in range(count):
                t = DurableTenant(mk_ds(), root=root,
                                  tenant=f"rec{count}-{i}",
                                  policy=policy, snapshot_every=6)
                for a, rm in deltas(10, 100 + i):
                    t.apply_delta(adds=a, removes=rm)
                t.close()
                names.append(f"rec{count}-{i}")
            t0 = time.perf_counter()
            reports = [recover_tenant(root=root, tenant=nm,
                                      policy=policy)[1]
                       for nm in names]
            rec[f"tenants{count}"] = {
                "recovery_ms": round(
                    (time.perf_counter() - t0) * 1e3, 1),
                "replayed": sum(r["replayed"] for r in reports)}
        out["recovery"] = rec
        # (c) live migration under traffic: requests before/during/after
        # the flip, zero non-expired failures, blip wall
        sets = [mk_ds() for _ in range(3)]
        pod = podmesh.PodMesh.simulate(2)
        fd = PodFrontDoor(sets, pod=pod, policy=ServingPolicy(
            pool_target=8, default_deadline_ms=600_000.0,
            max_queue=4096,
            guard=guard.GuardPolicy(backoff_base=0.0,
                                    sleep=lambda _s: None)))
        sid = next(s for s in range(3)
                   if fd.plan.regime(s) != "sharded")
        target = next(h for h in fd.pod.alive()
                      if h != fd.owner_host(sid))
        shapes = [("or", (0, 1, 2)), ("and", (1, 2, 3)),
                  ("xor", (0, 2))]
        served = []

        def traffic(k, seed):
            r = np.random.default_rng(seed)
            for i in range(k):
                served.append(fd.submit(ServingRequest(
                    sid, BatchQuery(*shapes[int(r.integers(3))]),
                    tenant=f"t{sid}")))
            fd.drain()

        traffic(24, 1)                                        # warm
        rep = migrate_tenant(fd, sid, target,
                             during=lambda _fd: traffic(24, 2))
        traffic(24, 3)
        bad = [t for t in served if t.status == "failed"
               or (t.status == "shed"
                   and getattr(t, "shed_reason", None) != "expired")]
        out["migration"] = {
            "requests": len(served), "failed_or_shed": len(bad),
            "migration_blip_ms": rep["blip_ms"],
            "stream_bytes": rep["bytes"],
            "catch_up_records": rep["catch_up_records"]}
        assert not bad, "migration lane left failed/shed requests"
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out))


def pod_replay_phase() -> dict:
    """Wire data-plane lane (ISSUE 20, docs/WIRE.md): the million-user
    pod replay harness driven through BOTH arms — in-process on the
    fault clock and over TCP against a REAL second OS process
    (wire.bootstrap) — reporting wire_vs_inproc_x (NEUTRAL: the
    network boundary's price, pinned not gated), the pipelined-vs-
    one-request-per-round-trip amortization on the same socket
    (HIGHER, the tentpole claim), and sustained QPS at >=90% SLO
    attainment with p99 under an overload ladder."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--pod-replay-cell"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=1200, env=_dryrun_env(8),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])
    except Exception as e:
        return {"error":
                f"pod_replay cell failed: {type(e).__name__}: {e}"}


def pod_replay_cell_main() -> None:
    """Subprocess body for pod_replay_phase (8 CPU devices): one
    bootstrap server process, one seeded workload, three measurements
    over the same socket."""
    from roaringbitmap_tpu.parallel import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.multiset import MultiSetBatchEngine
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                           replay)
    from roaringbitmap_tpu.wire import WireClient

    profile = replay.ReplayProfile(
        sets=2, sources=8, tenants=8, users=1 << 20, density=3000,
        requests=160, duration_s=1.0, seed=0x20)
    nosleep = guard.GuardPolicy(backoff_base=0.0, sleep=lambda _s: None)

    def mk_loop():
        bitmap_sets, columns = replay.build_dataset(profile)
        sets = [DeviceBitmapSet(b, layout="dense")
                for b in bitmap_sets]
        replay.attach_columns(sets, profile, columns)
        return ServingLoop(MultiSetBatchEngine(sets), ServingPolicy(
            pool_target=8, max_queue=4096,
            default_deadline_ms=60_000.0, guard=nosleep))

    events = replay.generate(profile)
    queries = [e[2] for e in events if e[0] == "query"]
    out: dict = {}

    server = subprocess.Popen(
        [sys.executable, "-m", "roaringbitmap_tpu.wire.bootstrap",
         "--seed", str(profile.seed), "--sets", str(profile.sets),
         "--sources", str(profile.sources),
         "--tenants", str(profile.tenants),
         "--density", str(profile.density),
         "--users", str(profile.users),
         "--pool-target", "8", "--max-queue", "4096",
         "--deadline-ms", "60000"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=_dryrun_env(8),
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        info = json.loads(server.stdout.readline())
        addr = (info["host"], info["port"])

        # warm both processes' compile caches off the clock
        warm = WireClient(addr, timeout=300)
        for r in queries[:6]:
            warm.call(r, 300)
        warm.close()
        loop = mk_loop()
        for r in queries[:6]:
            loop.submit(r)
        loop.drain()

        # (a) in-process arm on the fault clock (replay_stream
        # semantics) vs the SAME workload pipelined over the wire
        inproc = replay.run_inproc(mk_loop(), events)
        cl = WireClient(addr, timeout=300)
        wire = replay.run_wire(cl, events, pace=False, timeout=300)
        out["inproc"] = inproc
        out["wire"] = wire
        # NEUTRAL: the boundary's price on client-observed throughput
        out["wire_vs_inproc_x"] = round(
            wire["qps"] / max(inproc["qps"], 1e-9), 3)

        # (b) pipelining amortization on the SAME socket: coalesced
        # many-in-flight submission vs one request per round trip.
        # Uniform cheap flat cardinality queries isolate the per-request
        # floor (syscall + framing + admission + dispatch) the
        # pipelining exists to amortize — the mixed replay pools above
        # are compute-bound, so their per-query engine time would
        # measure the workload, not the wire
        from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
        from roaringbitmap_tpu.serving.loop import ServingRequest
        rng = np.random.default_rng(7)
        rtt_reqs = []
        for i in range(64):
            picked = rng.choice(profile.sources, size=2, replace=False)
            rtt_reqs.append(ServingRequest(
                set_id=i % profile.sets,
                query=BatchQuery(str(rng.choice(["and", "or"])),
                                 tuple(int(v) for v in picked),
                                 "cardinality"),
                tenant=f"t{i % profile.tenants}"))
        # two warm passes: TCP segmentation can split a cold burst
        # into odd-sized pools whose XLA compiles would otherwise
        # land on the clock (shapes stabilize after one pass)
        for _ in range(2):
            for t in cl.submit_many(rtt_reqs):
                t.wait(300)
        for r in rtt_reqs[:4]:               # ... and the singleton path
            cl.call(r, 300)
        rtt_s = pipe_s = float("inf")
        for _ in range(3):                   # best-of-3, both arms
            t0 = time.perf_counter()
            for r in rtt_reqs:
                cl.call(r, 300)
            rtt_s = min(rtt_s, time.perf_counter() - t0)
            t0 = time.perf_counter()
            tickets = cl.submit_many(rtt_reqs)
            for t in tickets:
                t.wait(300)
            pipe_s = min(pipe_s, time.perf_counter() - t0)
            assert all(t.ok for t in tickets)
        out["rtt_arm"] = {
            "requests": len(rtt_reqs),
            "rtt_qps": round(len(rtt_reqs) / rtt_s, 1),
            "pipelined_qps": round(len(rtt_reqs) / pipe_s, 1),
            # the tentpole claim: >=3x on the same socket
            "pipelined_vs_rtt_x": round(rtt_s / max(pipe_s, 1e-9), 3)}
        out["pipelined_vs_rtt_x"] = out["rtt_arm"]["pipelined_vs_rtt_x"]

        # (c) overload ladder, both arms: sustained QPS at >=90%
        # attainment + p99 at the sustained rung
        rates = [1.0, 4.0, 16.0]
        out["sustained_inproc"] = replay.sustained(
            lambda r: replay.run_inproc(mk_loop(), events,
                                        rate_scale=r), rates)
        out["sustained_wire"] = replay.sustained(
            lambda r: replay.run_wire(cl, events, rate_scale=r,
                                      pace=True, timeout=300), rates)
        out["sustained_qps_wire"] = \
            out["sustained_wire"]["sustained_qps"]
        out["sustained_qps_inproc"] = \
            out["sustained_inproc"]["sustained_qps"]
        out["overload_p99_ms"] = \
            out["sustained_wire"]["sustained_p99_ms"]
        cl.close()
    finally:
        server.stdin.close()
        try:
            server.wait(timeout=15)
        except subprocess.TimeoutExpired:
            server.kill()
    print(json.dumps(out))


#: hard byte cap on the final stdout summary line.  The driver captures a
#: BOUNDED tail of stdout (ADVICE r5: the r05 summary still came back
#: "parsed": null with the JSON head truncated), so the line must fit a
#: small fixed budget under ALL inputs; everything that does not fit
#: lives in benchmarks/bench_full.json.  tests/test_bench_output.py
#: asserts the cap holds even for adversarially bloated documents.
SUMMARY_MAX_BYTES = 2048

#: summary fields shed in order (least driver-critical first) until the
#: line fits SUMMARY_MAX_BYTES; the core (metric, value, vs_baseline,
#: full_doc) is never dropped — north_star goes last and only under a
#: pathological dataset count.  The ISSUE 6 cost/SLO lanes shed FIRST:
#: they are trend inputs for the sentry, not driver-gate fields, and the
#: full doc always keeps them
SUMMARY_DROP_ORDER = ("phase_ms", "cost", "pod_replay", "durability",
                      "resident",
                      "olap", "pod",
                      "lattice",
                      "mutation", "serving", "sharded", "expression",
                      "marginal_us_spread", "multiset", "batched_qps",
                      "marginal_us_median", "unit", "backend",
                      "north_star")

#: backend-declarative lane schema (ROADMAP item 1 groundwork): each
#: top-level lane group of the FULL document declares the platforms it
#: runs on and the engine rungs it exercises, so a diff between
#: documents captured on different hardware (the BENCH_r06 TPU capture
#: vs the committed CPU rounds) can SKIP a lane absent on the other
#: side's platform instead of reporting it removed
#: (tools/bench_diff.py reads this together with the doc's
#: ``platform``).  ``"any"`` = every backend bench.py runs on; the
#: schema ships in benchmarks/bench_full.json only — the byte-capped
#: stdout summary never carries it.
LANE_SCHEMA = {
    "batched_by_dataset": {
        "platforms": "any",
        "rungs": ["pallas", "xla", "xla-vmap", "sequential"]},
    "multiset": {"platforms": "any",
                 "rungs": ["xla", "megakernel", "sequential"]},
    "expression": {"platforms": "any", "rungs": ["xla", "megakernel"]},
    "serving": {"platforms": "any", "rungs": ["auto"]},
    "sharded": {"platforms": "any", "rungs": ["xla"]},
    "mutation": {"platforms": "any", "rungs": ["auto"]},
    "lattice": {"platforms": "any", "rungs": ["auto"]},
    "olap": {"platforms": "any", "rungs": ["auto", "megakernel"]},
    "resident": {"platforms": "any", "rungs": ["megakernel"]},
    "pod": {"platforms": "any", "rungs": ["auto"]},
    "durability": {"platforms": "any", "rungs": ["auto"]},
    "pod_replay": {"platforms": "any", "rungs": ["auto"]},
    # xprof kernel attribution needs real device traces
    "detail.profile_kernel_us": {"platforms": ["tpu"], "rungs": []},
    "detail.profile_trace_dir": {"platforms": ["tpu"], "rungs": []},
}


def summary_line(out: dict, full_path: str,
                 max_bytes: int = SUMMARY_MAX_BYTES) -> str:
    """The one stdout line: build_summary serialized compactly, shedding
    optional fields (SUMMARY_DROP_ORDER) until it fits ``max_bytes``."""
    s = build_summary(out, full_path)

    def dumps(d: dict) -> str:
        return json.dumps(d, separators=(",", ":"))

    line = dumps(s)
    for key in SUMMARY_DROP_ORDER:
        if len(line.encode("utf-8")) <= max_bytes:
            return line
        s.pop(key, None)
        line = dumps(s)
    if len(line.encode("utf-8")) > max_bytes:
        # last resort (adversarially long strings): the bare core
        s = {k: s.get(k) for k in ("metric", "value", "vs_baseline",
                                   "full_doc")}
        line = dumps(s)
    return line


def build_summary(out: dict, full_path: str) -> dict:
    """The compact driver-facing line: every field the north-star gate
    reads, none of the multi-KB detail (that lives in bench_full.json)."""
    detail = out.get("detail", {})
    s = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": "wide-OR/s",
        "vs_baseline": out["vs_baseline"],
        "backend": detail.get("backend"),
        "north_star": detail.get("north_star"),
        "full_doc": os.path.relpath(
            full_path, os.path.dirname(os.path.abspath(__file__))),
    }
    spread = detail.get("north_star_spread") or {}
    med = {name: row.get("marginal_us_median")
           for name, row in spread.items()
           if isinstance(row, dict) and "marginal_us_median" in row}
    if med:
        s["marginal_us_median"] = med
        s["marginal_us_spread"] = {
            name: [spread[name]["marginal_us_min"],
                   spread[name]["marginal_us_max"]] for name in med}
    batched = {}
    for name, row in (out.get("batched_by_dataset") or {}).items():
        if row:
            batched[name] = {
                k: row[k] for k in (
                    "q1_seq_dispatch_qps", "q8_e2e_qps", "q64_e2e_qps",
                    "q256_e2e_qps", "q64_steady_qps",
                    "q64_vs_q1_amortization_x", "meets_5x") if k in row}
            fl = row.get("fault_lane") or {}
            if "demotion_overhead_x" in fl:
                # degraded-mode cost, compact: x-overhead one rung down
                # and at the sequential floor (docs/ROBUSTNESS.md)
                batched[name]["degraded_x"] = [
                    fl["demotion_overhead_x"],
                    fl["sequential_floor_cost_x"]]
    if batched:
        s["batched_qps"] = batched
    # cost/SLO lanes, compact: roofline fraction + per-phase wall of the
    # max-Q batched execute per dataset (first shed under pressure)
    cost, phases = {}, {}
    for name, row in (out.get("batched_by_dataset") or {}).items():
        if isinstance(row, dict) and "cost" in row:
            cost[name] = row["cost"].get("roofline_fraction")
        if isinstance(row, dict) and row.get("phase_ms"):
            phases[name] = row["phase_ms"]
    if cost:
        s["cost"] = cost
    if phases:
        s["phase_ms"] = phases
    ms = out.get("multiset") or {}
    lanes = {}
    for key, row in ms.items():
        if isinstance(row, dict) and "pooled_qps" in row:
            # pooled vs per-set QPS per (S, Q) cell, compact
            lanes[key] = [row["pooled_qps"], row["per_set_qps"],
                          row["pooled_vs_per_set_x"]]
    if lanes:
        lanes["overlap_ratio"] = (ms.get("headline") or {}).get(
            "overlap_ratio")
        s["multiset"] = lanes
    # expression lane, compact: [fused_qps, node_qps, fused_vs_node_x,
    # launches_saved] per (depth, Q) cell
    ex = out.get("expression") or {}
    ex_lanes = {}
    for key, row in ex.items():
        if isinstance(row, dict) and "fused_qps" in row:
            ex_lanes[key] = [row["fused_qps"], row["node_qps"],
                             row["fused_vs_node_x"],
                             row["launches_saved"]]
    if ex_lanes:
        mega = ex.get("mega") or {}
        if "mega_vs_multiop_x" in mega:
            # one-kernel lane, compact: [mega_qps, bytes-drop ratio]
            ex_lanes["mega_vs_multiop_x"] = [
                mega.get("mega_qps"), mega["mega_vs_multiop_x"]]
        s["expression"] = ex_lanes
    # serving lane, compact: [p50_ms, p99_ms, slo_attainment, shed_rate]
    # per arrival-rate cell + the overload-vs-control attainment headline
    sv = out.get("serving") or {}
    sv_lanes = {}
    for key, row in sv.items():
        if isinstance(row, dict) and "slo_attainment" in row:
            sv_lanes[key] = [row.get("p50_ms"), row.get("p99_ms"),
                             row["slo_attainment"], row["shed_rate"]]
    if sv_lanes:
        head = sv.get("headline") or {}
        sv_lanes["overload_attainment"] = head.get("overload_attainment")
        sv_lanes["noshed_attainment"] = head.get("noshed_attainment")
        s["serving"] = sv_lanes
    # sharded lane, compact: [pooled_qps, shard_balance] per (mesh, Q)
    # cell + the mesh-vs-single headline ratio and the warm-restart
    # cold-path ratio (full cell detail stays in the full doc)
    sh = out.get("sharded") or {}
    sh_lanes = {}
    for key, row in sh.items():
        if isinstance(row, dict) and "pooled_qps" in row:
            sh_lanes[key] = [row["pooled_qps"], row["shard_balance"]]
    if sh_lanes:
        head = sh.get("headline") or {}
        sh_lanes["sharded_vs_single_x"] = head.get("sharded_vs_single_x")
        wr = sh.get("warm_restart") or {}
        if "warm_restart_x" in wr:
            sh_lanes["warm_restart_x"] = wr["warm_restart_x"]
        s["sharded"] = sh_lanes
    # mutation lane, compact: the in-place delta's speedup over a full
    # re-pack and the result cache's replay speedup over recompute
    # (bench.py mutation_phase, docs/MUTATION.md)
    mu = out.get("mutation") or {}
    if mu.get("headline"):
        mu_lane = dict(mu["headline"])
        if "delta" in mu:
            mu_lane["delta_ms"] = mu["delta"].get("delta_ms")
            mu_lane["repack_ms"] = mu["delta"].get("repack_ms")
        s["mutation"] = mu_lane
    # closed-lattice lane, compact: compile counts cold vs warmed,
    # escapes, the warmed p99/p50 ratio, and the padding byte fraction
    # (bench.py lattice_phase, docs/LATTICE.md)
    la = out.get("lattice") or {}
    if la.get("headline"):
        s["lattice"] = dict(la["headline"])
    # analytics OLAP lane, compact: [fused_qps, twophase_qps, ratio]
    # per Q cell + the fused-vs-two-phase headline and the warmed
    # zero-compile claim (bench.py olap_phase, docs/ANALYTICS.md)
    ol = out.get("olap") or {}
    ol_lanes = {}
    for key, row in ol.items():
        if isinstance(row, dict) and "fused_qps" in row:
            ol_lanes[key] = [row["fused_qps"], row["twophase_qps"],
                             row["fused_vs_twophase_x"]]
    if ol_lanes:
        head = ol.get("headline") or {}
        ol_lanes["fused_vs_twophase_x"] = head.get("fused_vs_twophase_x")
        if "mega_olap_x" in head:
            ol_lanes["mega_olap_x"] = head["mega_olap_x"]
        ol_lanes["warmed_compiles"] = head.get("warmed_compiles")
        ol_lanes["zero_compile_warmed"] = head.get("zero_compile_warmed")
        s["olap"] = ol_lanes
    # resident-queue lane, compact: the ring-vs-dispatch wall ratio and
    # the zero-host-dispatch pin (bench.py resident_phase,
    # docs/SERVING.md "Resident pump")
    re_ = out.get("resident") or {}
    if re_.get("headline"):
        s["resident"] = dict(re_["headline"])
        s["resident"]["ring_served"] = (re_.get("resident_arm")
                                        or {}).get("ring_served")
    # pod lane, compact: routed-vs-single QPS, routing overhead,
    # host-drop recovery, and the 2-process cluster scale-out ratio
    # (bench.py pod_phase, docs/POD.md)
    po = out.get("pod") or {}
    if "pod_vs_single_x" in po:
        po_lane = {"pod_vs_single_x": po["pod_vs_single_x"],
                   "route_us": po["route_us"],
                   "host_drop_recovery_ms": po["host_drop_recovery_ms"]}
        c2 = po.get("cluster2") or {}
        if "cluster2_vs_single_x" in c2:
            po_lane["cluster2_vs_single_x"] = c2["cluster2_vs_single_x"]
        s["pod"] = po_lane
    # durability lane, compact: journal overhead (NEUTRAL — a pinned
    # price, not a gate), recovery wall per tenant-count cell, and the
    # live-migration blip + zero-failure pin (bench.py
    # durability_phase, docs/DURABILITY.md)
    du = out.get("durability") or {}
    if du.get("journal"):
        du_lane = {"journal_overhead_x":
                   du["journal"].get("journal_overhead_x")}
        for key, row in (du.get("recovery") or {}).items():
            du_lane[f"recovery_ms_{key}"] = row.get("recovery_ms")
        grp = du["journal"].get("group") or {}
        if "fsync_per_delta" in grp:
            # group commit: fsyncs amortized across tenants' appends
            du_lane["group_fsync_per_delta"] = grp["fsync_per_delta"]
            du_lane["group_overhead_x"] = grp.get("group_overhead_x")
        mig = du.get("migration") or {}
        if "migration_blip_ms" in mig:
            du_lane["migration_blip_ms"] = mig["migration_blip_ms"]
            du_lane["migration_failed"] = mig.get("failed_or_shed")
        s["durability"] = du_lane
    # pod_replay lane, compact: the wire boundary's price (NEUTRAL),
    # the pipelining amortization headline (>=3x is the tentpole
    # claim), and sustained QPS at >=90% attainment + p99 under the
    # overload ladder, both arms (bench.py pod_replay_phase,
    # docs/WIRE.md)
    pr = out.get("pod_replay") or {}
    if "pipelined_vs_rtt_x" in pr:
        s["pod_replay"] = {
            "wire_vs_inproc_x": pr.get("wire_vs_inproc_x"),
            "pipelined_vs_rtt_x": pr["pipelined_vs_rtt_x"],
            "sustained_qps_wire": pr.get("sustained_qps_wire"),
            "sustained_qps_inproc": pr.get("sustained_qps_inproc"),
            "overload_p99_ms": pr.get("overload_p99_ms")}
    return s


def parse_profile_trace(trace_dir: str) -> dict:
    """Per-kernel DEVICE-time totals (us) from the latest Chrome trace —
    the jmh -prof analog promised by --profile.  Only events under device
    processes ("/device:TPU:*" process_name rows) are summed; host threads
    (jit dispatch spans that *enclose* kernel launches) would otherwise
    double-count and drown the kernel rows."""
    try:
        import glob
        import gzip

        paths = sorted(glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
        if not paths:
            return {"error": "no trace.json.gz found"}
        with gzip.open(paths[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
        device_pids = {
            ev.get("pid") for ev in events
            if ev.get("ph") == "M" and ev.get("name") == "process_name"
            and any(t in str(ev.get("args", {}).get("name", ""))
                    for t in ("/device:", "TPU", "Device"))}
        totals: dict[str, float] = {}
        for ev in events:
            if (ev.get("ph") == "X" and "dur" in ev
                    and ev.get("pid") in device_pids):
                name = ev.get("name", "?")
                totals[name] = totals.get(name, 0.0) + ev["dur"]
        if not totals:
            return {"error": "no device-process events in trace"}
        top = sorted(totals.items(), key=lambda kv: -kv[1])[:12]
        return {k: round(v, 1) for k, v in top}
    except Exception as e:  # pragma: no cover
        return {"error": f"trace parse failed: {e}"}


def spread_runs(n: int, own: dict[str, float]) -> dict:
    """Median + spread of the best-engine steady-state marginal per
    north-star dataset over n fresh-process measurements (this process's
    capture counts as one).  Each subprocess re-runs the same ingest +
    chained-marginal pipeline under a fresh XLA compilation/scheduling
    draw — the quantity that moved 5x between r03 and r04."""
    import jax

    parent_backend = jax.default_backend()
    samples = {name: [us] for name, us in own.items()}
    errors: list[str] = []
    for _ in range(max(0, n - 1)):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--spread-cell"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                timeout=900, cwd=os.path.dirname(os.path.abspath(__file__)))
            row = json.loads(proc.stdout.decode().strip().splitlines()[-1])
            if row.pop("backend", None) != parent_backend:
                # a child that lost the device and fell back to another
                # backend must not pollute the spread with alien timings
                errors.append("backend mismatch")
                continue
            for name, us in row.items():
                samples.setdefault(name, []).append(us)
        except Exception as e:
            errors.append(type(e).__name__)
    out = {}
    for name, xs in samples.items():
        out[name] = {
            "n": len(xs),
            "marginal_us_median": round(float(np.median(xs)), 2),
            "marginal_us_min": round(min(xs), 2),
            "marginal_us_max": round(max(xs), 2),
            "samples_us": [round(x, 2) for x in xs],
        }
    out["backend"] = parent_backend
    if errors:
        out["failed_runs"] = errors
    return out


def spread_cell_main() -> None:
    """Subprocess body for spread_runs: measure both north-star marginals
    once and print {dataset: best_marginal_us} as the only stdout line."""
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/rb_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp

    jnp.square(jax.device_put(np.ones(8, np.float32))).block_until_ready()
    states = {name: ingest_phase(name) for name in BENCH_DATASETS}
    row = {"backend": jax.default_backend()}
    for name in BENCH_DATASETS:
        r = query_phase(states[name], profile=False)
        row[name] = min(r["marginal_us_per_wide_or"].values())
    print(json.dumps(row))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", action="store_true",
                    help="capture a jax.profiler trace of the measured runs")
    ap.add_argument("--spread", type=int, default=5,
                    help="fresh-process re-measurements of the north-star "
                         "marginals (0/1 disables the extra processes)")
    ap.add_argument("--spread-cell", action="store_true",
                    help="internal: emit one spread sample and exit")
    ap.add_argument("--sharded-cell", action="store_true",
                    help="internal: run the sharded mesh sweep in a CPU "
                         "dry-run subprocess and exit")
    ap.add_argument("--warm-restart-cell", action="store_true",
                    help="internal: one warm-restart probe run and exit")
    ap.add_argument("--pod-cell", action="store_true",
                    help="internal: run the simulated-pod cells in a "
                         "CPU dry-run subprocess and exit")
    ap.add_argument("--durability-cell", action="store_true",
                    help="internal: run the durable-tenant cells in a "
                         "CPU dry-run subprocess and exit")
    ap.add_argument("--pod-replay-cell", action="store_true",
                    help="internal: run the wire replay lane (real "
                         "second process over TCP) in a CPU dry-run "
                         "subprocess and exit")
    ap.add_argument("--pod-worker", nargs=3, metavar=("PID", "PORT", "N"),
                    help="internal: one pod-cluster worker (process id, "
                         "coordinator port, process count) and exit")
    args = ap.parse_args()

    if args.spread_cell:
        spread_cell_main()
        return
    if args.sharded_cell:
        sharded_cell_main()
        return
    if args.warm_restart_cell:
        warm_restart_cell_main()
        return
    if args.pod_worker:
        pod_worker_main(int(args.pod_worker[0]), args.pod_worker[1],
                        int(args.pod_worker[2]))
        return
    if args.pod_cell:
        pod_cell_main()
        return
    if args.durability_cell:
        durability_cell_main()
        return
    if args.pod_replay_cell:
        pod_replay_cell_main()
        return

    # stdout hygiene: everything during the run (library prints, warnings
    # routed through stdout) goes to stderr; ONLY the final document is
    # written to the real stdout
    real_stdout = os.fdopen(os.dup(1), "w")
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    import jax

    # persistent compilation cache: the densify/reduce programs compile in
    # ~17s cold; cached on disk they load in ~1s on every later run
    jax.config.update("jax_compilation_cache_dir", "/tmp/rb_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import jax.numpy as jnp

    # runtime warm-up: first transfer/compile carries the axon handshake
    # (~600 ms) — real, but one-time per process, so report it apart
    t0 = time.perf_counter()
    jnp.square(jax.device_put(np.ones(8, np.float32))).block_until_ready()
    warmup_ms = (time.perf_counter() - t0) * 1e3

    # phase 1 for ALL datasets first: ingest timings must precede the first
    # D2H readback (see ingest_phase docstring for the measured tunnel mode
    # switch); phase 2 then queries each resident set; phase 3 runs the
    # batched multi-query lane over the still-resident sets
    states = {name: ingest_phase(name) for name in BENCH_DATASETS}
    results = {name: query_phase(states[name], args.profile)
               for name in BENCH_DATASETS}
    batched = {}
    for name in BENCH_DATASETS:
        batched[results[name]["dataset"]] = batched_phase(states[name])
        results[name]["batched"] = batched[results[name]["dataset"]]
    multiset = multiset_phase()
    expression = expression_phase()
    serving = serving_phase()
    sharded = sharded_phase()
    mutation = mutation_phase()
    lattice = lattice_phase()
    olap = olap_phase()
    resident = resident_phase()
    pod = pod_phase()
    durability = durability_phase()
    pod_replay = pod_replay_phase()

    # Medianize BEFORE assembling the document, so the headline is built
    # exactly once.  A single steady-state marginal at VMEM-resident
    # working-set sizes swings several x between compilations (r03/r04
    # wikileaks); the median of the fresh-process spread is the honest
    # headline, with this process's own draw kept under "single_draw".
    spread = None
    if args.spread > 1:
        own = {name: min(r["marginal_us_per_wide_or"].values())
               for name, r in results.items()}
        spread = spread_runs(args.spread, own)
        for name, r in results.items():
            if name in spread and spread[name]["n"] >= 3:
                med_s = spread[name]["marginal_us_median"] / 1e6
                r["single_draw"] = {"ops_per_sec": r["ops_per_sec"],
                                    "vs_baseline": r["vs_baseline"]}
                r["ops_per_sec"] = round(1.0 / med_s, 3)
                r["vs_baseline"] = round(
                    r["cpu_wide_or_ms"] / 1e3 / med_s, 3)

    head = results[BENCH_DATASETS[0]]
    # label as a median ONLY when the headline really is one
    if spread and spread.get(BENCH_DATASETS[0], {}).get("n", 1) >= 3:
        unit = ("wide-OR/s (200 bitmaps, card-exact, median steady-state "
                f"marginal over {spread[BENCH_DATASETS[0]]['n']} fresh "
                "processes)")
    else:
        unit = "wide-OR/s (200 bitmaps, card-exact, steady-state marginal)"
    out = {
        "metric": f"wide_or_{head['dataset']}_aggregations_per_sec",
        "value": head["ops_per_sec"],
        "unit": unit,
        "vs_baseline": head["vs_baseline"],
        "detail": {
            "backend": jax.default_backend(),
            "warmup_ms": round(warmup_ms, 1),
            **{k: v for k, v in head.items() if k != "dataset"},
            "wikileaks-noquotes": results.get("wikileaks-noquotes"),
            "north_star": {
                name: {"vs_baseline": r["vs_baseline"],
                       "target": 10.0, "met": r["vs_baseline"] >= 10.0}
                for name, r in results.items()},
        },
    }
    if spread is not None:
        out["detail"]["north_star_spread"] = spread
    if args.profile:
        out["detail"]["profile_trace_dir"] = "/tmp/rb_tpu_trace"
        out["detail"]["profile_kernel_us"] = parse_profile_trace(
            "/tmp/rb_tpu_trace")
    out["batched_by_dataset"] = batched
    out["multiset"] = multiset
    out["expression"] = expression
    out["serving"] = serving
    out["sharded"] = sharded
    out["mutation"] = mutation
    out["lattice"] = lattice
    out["olap"] = olap
    out["resident"] = resident
    out["pod"] = pod
    out["durability"] = durability
    out["pod_replay"] = pod_replay
    out["platform"] = jax.default_backend()
    out["lane_schema"] = LANE_SCHEMA

    # full document to disk; stdout gets ONLY the compact summary as its
    # final line (the driver's bounded tail capture must parse it)
    full_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks", "bench_full.json")
    with open(full_path, "w") as f:
        json.dump(out, f, indent=1)
    print(summary_line(out, full_path), file=real_stdout)
    real_stdout.flush()


if __name__ == "__main__":
    main()
