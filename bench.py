"""Benchmark: wide-OR aggregation throughput on census1881 (driver metric).

Measures the north-star workload from BASELINE.json: FastAggregation/
ParallelAggregation-style wide OR over the census1881 real-roaring-dataset
(200 bitmaps), executed on device from HBM-resident packed containers, with
exact cardinality materialized back to host every iteration.

Prints ONE JSON line:
  metric       wide-OR aggregations/sec over the full dataset
  vs_baseline  speedup vs this host's CPU fold (our host container tier,
               the stand-in for the JVM ParallelAggregation baseline)
Cardinality parity with the NumPy oracle is asserted before timing.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from roaringbitmap_tpu import RoaringBitmap, or_ as host_or
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.utils import datasets

    if datasets.has_dataset("census1881"):
        arrs = datasets.load_value_arrays("census1881")
        dataset = "census1881"
    else:
        dataset = "synthetic"
        rng = np.random.default_rng(0)
        arrs = [rng.integers(0, 1 << 24, 50000).astype(np.uint32) for _ in range(200)]

    bitmaps = [RoaringBitmap.from_values(a) for a in arrs]
    oracle_card = int(np.unique(np.concatenate(arrs)).size)

    # ---- CPU baseline: host-tier pairwise fold (JVM ParallelAggregation stand-in)
    t0 = time.perf_counter()
    acc = bitmaps[0].clone()
    for b in bitmaps[1:]:
        acc.ior(b)
    cpu_s = time.perf_counter() - t0
    assert acc.cardinality == oracle_card, "host fold parity failure"

    # ---- device path: pack once (HBM-resident), aggregate repeatedly
    import jax.numpy as jnp

    backend = jax.default_backend()
    ds = DeviceBitmapSet(bitmaps)

    def run_chained(engine: str, reps: int) -> float:
        """Steady state: `reps` data-dependent wide-ORs in one dispatch; the
        returned total proves every iteration ran bit-exact (no elision)."""
        assert reps * oracle_card < 2**31
        fn = ds.chained_wide_or(reps, engine=engine)
        total = int(np.asarray(fn(ds.words)))  # compile + warmup
        assert total == reps * oracle_card, \
            f"device parity failure ({engine}): {total} != {reps}*{oracle_card}"
        t0 = time.perf_counter()
        total = int(np.asarray(fn(ds.words)))
        dt = (time.perf_counter() - t0) / reps
        assert total == reps * oracle_card
        return dt

    # single-shot sanity: the one-call path must agree with the host fold
    words, cards = ds.aggregate_device("or", engine="xla")
    assert int(np.asarray(cards.sum())) == oracle_card, "device parity failure"

    # calibration: pick the faster engine on this backend, then measure
    per_engine = {eng: run_chained(eng, 50) for eng in ("xla", "pallas")}
    engine = min(per_engine, key=per_engine.get)
    dev_s = run_chained(engine, 500)

    ops_per_sec = 1.0 / dev_s
    print(json.dumps({
        "metric": f"wide_or_{dataset}_aggregations_per_sec",
        "value": round(ops_per_sec, 3),
        "unit": "wide-OR/s (200 bitmaps, card-exact)",
        "vs_baseline": round(cpu_s / dev_s, 3),
        "detail": {
            "backend": backend, "engine": engine,
            "per_engine_ms": {k: round(v * 1e3, 3) for k, v in per_engine.items()},
            "n_bitmaps": len(bitmaps), "result_cardinality": oracle_card,
            "device_ms_per_wide_or": round(dev_s * 1e3, 3),
            "cpu_fold_ms": round(cpu_s * 1e3, 1),
            "hbm_resident_mb": round(ds.hbm_bytes() / 1e6, 1),
        },
    }))


if __name__ == "__main__":
    main()
